"""Dogfood trace pipeline (`selftrace_ingest_enabled`): self-traces are
ingested into the reserved ``_selftrace`` tenant and searchable end to
end, dispatch profiler records lower into per-stage child spans,
request-scope QueryStats attach as ``query.*`` span attributes, and the
anomaly flight recorder snapshots bounded diagnostic bundles whose
trace ids resolve via ordinary trace-by-ID.

The acceptance centerpiece: ONE external search request, with the gate
on, yields a ``_selftrace`` trace that is (a) retrievable by
trace-by-ID and (b) matched by a structural ``?q=`` over span.stage —
within one flush+poll cycle. Plus: gate off is byte-identical noop, and
an injected breaker trip produces a flight-recorder bundle whose trace
id resolves.
"""

import json
import os

import pytest

from tempo_tpu import robustness, tempopb
from tempo_tpu.api.http import HTTPApi
from tempo_tpu.db.tempodb import TempoDBConfig
from tempo_tpu.modules import App, AppConfig
from tempo_tpu.observability import selftrace, tracing
from tempo_tpu.observability.flightrecorder import (RECORDER,
                                                    TRIGGER_BREAKER,
                                                    TRIGGER_SLOW_QUERY,
                                                    TRIGGER_WATCHDOG,
                                                    FlightRecorder)
from tempo_tpu.observability.selftrace import SELFTRACE
from tempo_tpu.observability.tracing import (SELFTRACE_TENANT,
                                             CollectExporter,
                                             InProcessExporter,
                                             SyncProcessor, Tracer)
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.utils.test_data import make_trace


@pytest.fixture(autouse=True)
def _clean_selftrace():
    yield
    tracing.set_tracer(None)
    selftrace.configure(ingest_enabled=False, flight_recorder_max=32)
    RECORDER.reset()
    robustness.FAULTS.disarm_all()
    robustness.BREAKER.reset()
    robustness.BREAKER.enabled = True
    robustness.BREAKER.threshold = 3


def _dogfood_app(tmp_path, **db_kw):
    db_kw.setdefault("search_structural_enabled", True)
    db_kw.setdefault("auto_mesh", False)
    return App(AppConfig(
        wal_dir=str(tmp_path / "wal"),
        db=TempoDBConfig(**db_kw),
        self_tracing={"enabled": True, "exporter": "self",
                      "selftrace_ingest_enabled": True,
                      "sample_ratio": 1.0,
                      "flush_interval_s": 0.05},
    ))


def _seed_corpus(app, tenant="t1", n=3):
    for seed in range(1, n + 1):
        app.push(tenant, list(make_trace(random_trace_id(),
                                         seed=seed).batches))
    app.flush_tick(force=True)
    app.poll_tick()


# ------------------------------------------------ the dogfood loop


def test_dogfood_round_trip_one_request_one_cycle(tmp_path):
    """One external search → a `_selftrace` trace retrievable by
    trace-by-ID AND matched by a structural query on span.stage, within
    one flush+poll cycle."""
    app = _dogfood_app(tmp_path)
    try:
        assert SELFTRACE.ingest_enabled
        assert RECORDER.enabled
        assert isinstance(app.tracer.processor.exporter, InProcessExporter)
        api = HTTPApi(app)
        _seed_corpus(app)

        # warm the jit cache: the profiler books a cache-miss dispatch
        # under "compile"; the SECOND (hit) request records "execute"
        for _ in range(2):
            code, body = api.handle(
                "GET", "/api/search",
                {"tags": "service.name=frontend", "limit": "10"},
                {"X-Scope-OrgID": "t1"})
            assert code == 200

        # one flush+poll cycle makes the self-spans block-searchable
        app.tracer.processor.force_flush()
        app.flush_tick(force=True)
        app.poll_tick()

        hdr = {"X-Scope-OrgID": SELFTRACE_TENANT}

        # structural query over dispatch stage spans — "execute" is
        # recorded for every device dispatch
        q = json.dumps({"exists": {"tag": {"k": "stage", "v": "execute"}}})
        code, sbody = api.handle("GET", "/api/search",
                                 {"q": q, "limit": "20"}, hdr)
        assert code == 200
        hits = sbody.get("traces") or []
        assert hits, "structural span.stage query found no self-traces"

        # among the structural hits, the external request's own trace
        # must resolve by trace-by-ID and carry the dispatch children
        request_trace = None
        for hit in hits:
            code, trace = api.handle(
                "GET", f"/api/traces/{hit['traceId']}", {}, hdr)
            assert code == 200, f"trace-by-ID failed for {hit['traceId']}"
            flat = json.dumps(trace)
            if "/api/search" in flat:
                request_trace = flat
                break
        assert request_trace is not None, \
            "no structural hit resolved to the external search request"
        assert "dispatch.execute" in request_trace
        # QueryStats breakdown rode along as query.* span attributes
        assert "query.wall_ms" in request_trace
    finally:
        app.shutdown()


def test_gate_off_is_inert_and_reserved_tenant_untouched(tmp_path):
    """Default (gate off): plain SelfExporter, dead singletons, and no
    `_selftrace` tenant materializes anywhere in the pipeline."""
    app = App(AppConfig(
        wal_dir=str(tmp_path / "wal"),
        self_tracing={"enabled": True, "exporter": "self",
                      "flush_interval_s": 0.05},
    ))
    try:
        assert not SELFTRACE.ingest_enabled
        assert not RECORDER.enabled
        assert RECORDER.record(TRIGGER_BREAKER) is None
        assert not isinstance(app.tracer.processor.exporter,
                              InProcessExporter)

        _seed_corpus(app)
        api = HTTPApi(app)
        code, _ = api.handle("GET", "/api/search",
                             {"tags": "service.name=frontend"},
                             {"X-Scope-OrgID": "t1"})
        assert code == 200
        app.tracer.processor.force_flush()
        app.flush_tick(force=True)
        app.poll_tick()

        # self-spans went to the CONFIGURED tenant (legacy behavior),
        # never the reserved one
        req = tempopb.SearchRequest()
        req.tags["service.name"] = "tempo-tpu"
        assert len(app.frontend.search(SELFTRACE_TENANT, req).traces) == 0
        wal = tmp_path / "wal"
        if wal.exists():
            assert SELFTRACE_TENANT not in os.listdir(wal)
    finally:
        app.shutdown()


def test_gate_on_vs_off_external_responses_identical(tmp_path):
    """Contract check: the gate must not leak into user-visible
    responses — same corpus, same query, byte-identical /api/search
    answers with the gate on and off."""
    def run(enabled, where):
        cfg = {"enabled": True, "exporter": "self",
               "flush_interval_s": 0.05}
        if enabled:
            cfg["selftrace_ingest_enabled"] = True
        app = App(AppConfig(
            wal_dir=str(where / "wal"),
            db=TempoDBConfig(auto_mesh=False),
            self_tracing=cfg))
        try:
            for seed in (1, 2):
                app.push("t1", list(make_trace(
                    bytes([seed]) * 16, seed=seed).batches))
            app.flush_tick(force=True)
            app.poll_tick()
            api = HTTPApi(app)
            code, body = api.handle(
                "GET", "/api/search",
                {"tags": "service.name=frontend", "limit": "10"},
                {"X-Scope-OrgID": "t1"})
            assert code == 200
            return json.dumps(body, sort_keys=True)
        finally:
            app.shutdown()

    on = run(True, tmp_path / "on")
    off = run(False, tmp_path / "off")
    assert on == off


def test_sse_stream_metrics_and_self_trace(tmp_path):
    """Satellite: the SSE surfaces are instrumented — active-stream
    gauge balances to zero, per-tenant event counters tick, and the
    streaming leg leaves its own span in `_selftrace`."""
    from tempo_tpu.observability import metrics as obs

    app = _dogfood_app(tmp_path)
    try:
        api = HTTPApi(app)
        _seed_corpus(app)
        hdr = {"X-Scope-OrgID": "t1"}
        g0 = obs.sse_active_streams.value(endpoint="search_stream",
                                          tenant="t1")
        done0 = obs.sse_events_streamed.value(
            endpoint="search_stream", tenant="t1", event="done")
        code, body = api.handle("GET", "/api/search/stream",
                                {"limit": "10"}, hdr)
        assert code == 200
        frames = list(body.events)
        assert frames and frames[-1].startswith("event: done")
        assert obs.sse_active_streams.value(
            endpoint="search_stream", tenant="t1") == g0
        assert obs.sse_events_streamed.value(
            endpoint="search_stream", tenant="t1", event="done") \
            == done0 + 1

        app.tracer.processor.force_flush()
        app.flush_tick(force=True)
        app.poll_tick()
        shdr = {"X-Scope-OrgID": SELFTRACE_TENANT}
        code, sbody = api.handle("GET", "/api/search",
                                 {"tags": "service.name=tempo-tpu",
                                  "limit": "20"}, shdr)
        assert code == 200
        seen = []
        for hit in sbody.get("traces") or []:
            code, trace = api.handle(
                "GET", f"/api/traces/{hit['traceId']}", {}, shdr)
            assert code == 200
            seen.append(json.dumps(trace))
        assert any("sse.search_stream" in t for t in seen), \
            "streaming leg span missing from _selftrace"
    finally:
        app.shutdown()


# ------------------------------------------------ stage-span lowering


class _Rec:
    """Minimal stand-in for a finished profile.Dispatch record."""

    mode = "batched"
    jit = "miss"
    h2d_bytes = 4096
    d2h_bytes = 128

    def __init__(self, stages=None):
        self.stages = stages if stages is not None else {
            "build": 0.001, "h2d": 0.002, "compile": 0.003,
            "execute": 0.004, "d2h": 0.0005}


def _sync_tracer():
    exp = CollectExporter()
    tracer = Tracer(SyncProcessor(exp))
    tracing.set_tracer(tracer)
    return exp, tracer


def test_lower_dispatch_synthesizes_ordered_stage_children():
    exp, tracer = _sync_tracer()
    selftrace.configure(ingest_enabled=True)
    with tracer.start_span("req") as parent:
        SELFTRACE.lower_dispatch(_Rec(), parent=parent)
    children = [s for s in exp.spans if s.name.startswith("dispatch.")]
    assert [s.name for s in children] == [
        "dispatch.build", "dispatch.h2d", "dispatch.compile",
        "dispatch.execute", "dispatch.d2h"]
    for s in children:
        assert s.parent_span_id == parent.context.span_id
        assert s.context.trace_id == parent.context.trace_id
        assert s.attributes["mode"] == "batched"
        assert s.end_ns > s.start_ns
    by_name = {s.name: s for s in children}
    # durations survive the lowering (what structural dur predicates see)
    assert by_name["dispatch.execute"].end_ns - \
        by_name["dispatch.execute"].start_ns == 4_000_000
    # back-to-back, in stage order
    for a, b in zip(children, children[1:]):
        assert a.end_ns == b.start_ns
    # transfer bytes + jit verdict ride along
    assert by_name["dispatch.h2d"].attributes["bytes"] == 4096
    assert by_name["dispatch.d2h"].attributes["bytes"] == 128
    assert by_name["dispatch.execute"].attributes["jit_cache"] == "miss"
    assert by_name["dispatch.compile"].attributes["jit_cache"] == "miss"
    assert "jit_cache" not in by_name["dispatch.h2d"].attributes


def test_lower_dispatch_noop_paths():
    exp, tracer = _sync_tracer()
    selftrace.configure(ingest_enabled=True)
    # no recording parent (NOOP span) → nothing synthesized
    SELFTRACE.lower_dispatch(_Rec())
    assert exp.spans == []
    # empty stage map → nothing
    with tracer.start_span("req") as parent:
        SELFTRACE.lower_dispatch(_Rec(stages={}), parent=parent)
    assert [s.name for s in exp.spans] == ["req"]
    # gate off → nothing, even with a live parent
    selftrace.configure(ingest_enabled=False)
    with tracer.start_span("req2") as parent:
        SELFTRACE.lower_dispatch(_Rec(), parent=parent)
    assert [s.name for s in exp.spans] == ["req", "req2"]


def test_annotate_query_attaches_headline_costs():
    exp, tracer = _sync_tracer()
    selftrace.configure(ingest_enabled=True)
    d = {"wall_ms": 12.5, "device_seconds": 0.003,
         "blocks_inspected": 7,
         "bytes_inspected": {"host": 1000, "device": 2000},
         "dispatches": 4, "fused_dispatches": 2}
    with tracer.start_span("request") as span:
        SELFTRACE.annotate_query(d)
    attrs = exp.spans[0].attributes
    assert attrs["query.wall_ms"] == 12.5
    assert attrs["query.device_seconds"] == 0.003
    assert attrs["query.blocks_inspected"] == 7
    assert attrs["query.bytes_host"] == 1000
    assert attrs["query.bytes_device"] == 2000
    assert attrs["query.dispatches"] == 4
    assert attrs["query.fused_dispatches"] == 2
    assert "query.subqueries" not in attrs
    # gate off → span untouched
    selftrace.configure(ingest_enabled=False)
    with tracer.start_span("request2"):
        SELFTRACE.annotate_query(d)
    assert "query.wall_ms" not in exp.spans[1].attributes
    assert span is not None


# ------------------------------------------------ flight recorder


def test_flight_recorder_ring_and_snapshot():
    rec = FlightRecorder(max_bundles=2)
    assert rec.record(TRIGGER_SLOW_QUERY) is None  # disabled
    rec.enabled = True
    b1 = rec.record(TRIGGER_SLOW_QUERY, trace_id="aa" * 16,
                    detail={"wall_ms": 900})
    assert b1["seq"] == 1 and b1["trigger"] == TRIGGER_SLOW_QUERY
    assert b1["trace_id"] == "aa" * 16
    assert b1["detail"] == {"wall_ms": 900}
    # every subsystem key present (value may be None outside an App)
    for key in ("profile", "breaker", "planner", "ownership"):
        assert key in b1
    rec.record(TRIGGER_BREAKER)
    rec.record(TRIGGER_BREAKER)
    snap = rec.snapshot()
    assert snap["recorded"] == 3
    assert snap["by_trigger"] == {TRIGGER_SLOW_QUERY: 1, TRIGGER_BREAKER: 2}
    assert len(snap["bundles"]) == 2  # ring bound: oldest evicted
    assert [b["seq"] for b in snap["bundles"]] == [2, 3]
    json.loads(json.dumps(snap, default=str))  # /debug-renderable
    rec.resize(1)
    assert len(rec.snapshot()["bundles"]) == 1
    rec.reset()
    assert rec.snapshot()["recorded"] == 0


def test_flight_recorder_captures_current_trace_id():
    _, tracer = _sync_tracer()
    rec = FlightRecorder()
    rec.enabled = True
    with tracer.start_span("victim") as span:
        bundle = rec.record(TRIGGER_WATCHDOG)
    assert bundle["trace_id"] == span.context.trace_id.hex()


def test_breaker_trip_produces_resolvable_bundle(tmp_path):
    """An injected dispatch fault trips the breaker; the flight
    recorder snapshots a bundle whose trace id resolves in
    `_selftrace`; /debug/flightrecorder renders it."""
    app = _dogfood_app(tmp_path)
    try:
        api = HTTPApi(app)
        _seed_corpus(app)
        RECORDER.reset()
        robustness.BREAKER.reset()
        robustness.BREAKER.enabled = True
        robustness.BREAKER.threshold = 1
        with robustness.FAULTS.armed("device_dispatch_raise", count=1):
            code, _ = api.handle(
                "GET", "/api/search",
                {"tags": "service.name=frontend", "limit": "10"},
                {"X-Scope-OrgID": "t1"})
            assert code == 200  # host fallback keeps the answer intact

        snap = RECORDER.snapshot()
        trips = [b for b in snap["bundles"]
                 if b["trigger"] == TRIGGER_BREAKER]
        assert trips, f"no breaker_trip bundle recorded: {snap}"
        bundle = trips[-1]
        assert bundle["trace_id"], "bundle did not capture a trace id"
        assert bundle["breaker"] is not None
        assert bundle["profile"] is not None

        # the offending request's own self-trace resolves by ID
        app.tracer.processor.force_flush()
        app.flush_tick(force=True)
        app.poll_tick()
        code, trace = api.handle(
            "GET", f"/api/traces/{bundle['trace_id']}", {},
            {"X-Scope-OrgID": SELFTRACE_TENANT})
        assert code == 200, \
            f"flight-recorder trace id did not resolve: {bundle['trace_id']}"
        assert "/api/search" in json.dumps(trace)

        dbg = HTTPApi(app, debug_endpoints=True)
        code, page = dbg.handle("GET", "/debug/flightrecorder", {}, {})
        assert code == 200
        assert page["by_trigger"].get(TRIGGER_BREAKER, 0) >= 1
        json.loads(json.dumps(page, default=str))
    finally:
        app.shutdown()
