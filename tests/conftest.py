"""Test harness: force an 8-device virtual CPU platform before jax loads.

Multi-chip TPU hardware is not available in CI; all sharding/parallelism
tests run over a virtual 8-device CPU mesh, exactly as the driver's
dryrun_multichip does. This must run before any jax import anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # tests never touch the real chip
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# this image's sitecustomize imports jax (axon TPU plugin) before conftest
# runs, so the env vars alone are too late — override via jax.config, which
# works as long as no backend has been initialized yet
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax without the option: XLA_FLAGS above still applies, since
    # the CPU client reads it at backend init (first device use), which
    # has not happened yet — sitecustomize only IMPORTS jax
    pass

import pytest  # noqa: E402


@pytest.fixture
def tmp_backend_dir(tmp_path):
    d = tmp_path / "backend"
    d.mkdir()
    return str(d)


@pytest.fixture
def tmp_wal_dir(tmp_path):
    d = tmp_path / "wal"
    d.mkdir()
    return str(d)
