"""Test harness: force an 8-device virtual CPU platform before jax loads.

Multi-chip TPU hardware is not available in CI; all sharding/parallelism
tests run over a virtual 8-device CPU mesh, exactly as the driver's
dryrun_multichip does. This must run before any jax import anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402


@pytest.fixture
def tmp_backend_dir(tmp_path):
    d = tmp_path / "backend"
    d.mkdir()
    return str(d)


@pytest.fixture
def tmp_wal_dir(tmp_path):
    d = tmp_path / "wal"
    d.mkdir()
    return str(d)
