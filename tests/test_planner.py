"""Adaptive host/device offload planner (ISSUE 6 tentpole).

The contracts pinned here, from the acceptance criteria:

  - ``search_device_probe_min_vals <= 0`` forces host-only probing even
    with the planner enabled (the static threshold stays the floor);
  - planner-on vs planner-off results are byte-identical across the
    single-block, multi-block, coalesced, and mesh dispatch paths,
    whichever side the cost model picks (both placements are exact);
  - a cold process (empty profiler aggregates) makes a sane seeded
    decision instead of crashing or staging hundreds of MB blindly;
  - a fused/coalesced group plans once — repeated queries over a staged
    batch don't burn a decision per member;
  - decisions and predicted-vs-actual error surface at /debug/planner,
    and the offline replay tool rebuilds the model from a profiler dump.
"""

from __future__ import annotations

import json
import random
import threading

import numpy as np
import pytest

from tempo_tpu import tempopb
from tempo_tpu.search import dict_probe, pipeline, planner
from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
from tempo_tpu.search.data import SearchData
from tempo_tpu.search.engine import ScanEngine, stage
from tempo_tpu.search.multiblock import (
    MultiBlockEngine,
    compile_multi,
    stack_blocks,
    stack_queries,
)
from tempo_tpu.search.pipeline import compile_query


@pytest.fixture(autouse=True)
def _fresh_state():
    """Cold planner + compile cache per test; planner disabled on exit
    (it is process-wide, like the profiler)."""
    pipeline._COMPILE_CACHE.clear()
    planner.configure(enabled=False, seed=False, reset=True)
    yield
    planner.configure(enabled=False, seed=True, reset=True)
    pipeline._COMPILE_CACHE.clear()


def _force(target: str) -> planner.OffloadPlanner:
    """Enable the planner with injected observations that make `target`
    win every probe decision — deterministic tests, no microbenchmark."""
    p = planner.configure(enabled=True, seed=False, reset=True)
    p.seed_on_first_use = False
    slow, fast = 10.0, 1e-7
    if target == "device":
        p.observe("host_probe", slow, nbytes=1024)
        p.observe("device_probe", fast, nbytes=1024)
    else:
        p.observe("host_probe", fast, nbytes=1024)
        p.observe("device_probe", slow, nbytes=1024)
    p.observe("h2d", fast, nbytes=1024)
    p.observe("pack", fast, nbytes=1024)
    for k in ("dispatch", "compile", "collective"):
        p._update(k, fast, 0)
    return p


def _mk_req(tags=None, **kw):
    req = tempopb.SearchRequest()
    for k, v in (tags or {}).items():
        req.tags[k] = v
    for k, v in kw.items():
        setattr(req, k, v)
    return req


def _corpus(n, seed, card=300):
    rng = random.Random(seed)
    out = []
    for i in range(n):
        tid = (seed.to_bytes(2, "big") + i.to_bytes(4, "big")).rjust(16, b"\x00")
        sd = SearchData(trace_id=tid)
        sd.start_s = 1_600_000_000 + seed * 1_000_000 + i
        sd.end_s = sd.start_s + 5
        sd.dur_ms = rng.randint(1, 30_000)
        sd.kvs = {"session.id": {f"session-{rng.randint(0, card - 1):04d}"},
                  "svc": {rng.choice(["frontend", "cart"])}}
        out.append(sd)
    return out


def _blocks(n=3, entries=150, small_tail=True):
    blocks = [ColumnarPages.build(_corpus(entries, seed=s),
                                  PageGeometry(32, 8)) for s in range(n)]
    if small_tail:
        blocks.append(ColumnarPages.build(_corpus(80, seed=9, card=3),
                                          PageGeometry(32, 8)))
    return blocks


# ---------------------------------------------------------------------------
# floor / override semantics


def test_threshold_off_forces_host_even_with_planner_enabled():
    """`search_device_probe_min_vals <= 0` is host-only, planner or not:
    the call sites never reach the planner below the floor."""
    _force("device")  # planner would demand device everywhere
    pages = ColumnarPages.build(_corpus(200, seed=1), PageGeometry(32, 8))

    sp = stage(pages, probe_min_vals=0)
    assert sp.staged_dict is None
    sp = stage(pages, probe_min_vals=-1)
    assert sp.staged_dict is None
    batch = stack_blocks([pages], probe_min_vals=0)
    assert not batch.staged_dicts
    # no decision was ever burned: the floor short-circuits the planner
    snap = planner.PLANNER.snapshot()
    assert snap["decisions"] == {"host": 0, "device": 0}

    # ... and the batcher end to end: results identical to planner-off
    from tempo_tpu.search.batcher import BlockBatcher, ScanJob

    def jobs():
        return [ScanJob(key=("b0", 0, pages.n_pages),
                        pages_fn=lambda: pages, header=dict(pages.header),
                        n_pages=pages.n_pages, n_entries=pages.n_entries,
                        geometry=(pages.header["entries_per_page"],
                                  pages.header["kv_per_entry"]))]
    req = _mk_req({"session.id": "session-00"}, limit=500)
    r_on = BlockBatcher(coalesce_max_queries=1, device_probe_min_vals=0) \
        .search(jobs(), req).response().SerializeToString()
    planner.configure(enabled=False)
    pipeline._COMPILE_CACHE.clear()
    r_off = BlockBatcher(coalesce_max_queries=1, device_probe_min_vals=0) \
        .search(jobs(), req).response().SerializeToString()
    assert r_on == r_off


def test_planner_disabled_is_static_path():
    """Disabled planner == today's behavior: above the threshold the
    dictionary stages and the probe runs on device, no decisions."""
    planner.configure(enabled=False)
    pages = ColumnarPages.build(_corpus(200, seed=2), PageGeometry(32, 8))
    sp = stage(pages, probe_min_vals=1)
    assert sp.staged_dict is not None
    cq = compile_query(pages.key_dict, pages.val_dict, _mk_req(
        {"session.id": "session-00"}, limit=100), staged_dict=sp.staged_dict)
    assert cq.val_hits is not None
    assert planner.PLANNER.snapshot()["decisions"] == {"host": 0,
                                                       "device": 0}


# ---------------------------------------------------------------------------
# byte-identity across dispatch paths, both verdicts


def _single_block_result(probe_min_vals):
    pages = ColumnarPages.build(_corpus(300, seed=3), PageGeometry(64, 8))
    req = _mk_req({"session.id": "session-00"}, limit=1000)
    eng = ScanEngine(top_k=1024)
    sp = stage(pages, probe_min_vals=probe_min_vals)
    cq = compile_query(pages.key_dict, pages.val_dict, req,
                       staged_dict=sp.staged_dict)
    count, inspected, scores, idx = eng.scan_staged(sp, cq)
    res = [(m.trace_id, m.start_time_unix_nano)
           for m in eng.results(sp, cq, scores, idx)]
    return int(count), int(inspected), res, sp, cq


def test_single_block_byte_identical_both_verdicts():
    planner.configure(enabled=False)
    base = _single_block_result(0)[:3]

    for verdict in ("device", "host"):
        _force(verdict)
        pipeline._COMPILE_CACHE.clear()
        count, inspected, res, sp, cq = _single_block_result(1)
        if verdict == "device":
            assert sp.staged_dict is not None
            assert cq.val_hits is not None
        else:
            # stage-time veto: the planner kept the dictionary on host
            assert sp.staged_dict is None
            assert cq.val_hits is None
        assert (count, inspected, res) == base, verdict


def test_compile_time_veto_over_staged_dict():
    """A dictionary already resident in HBM can still be HOST-probed
    when the model says the kernel loses (the CPU 10M case): the staged
    bytes stay, only the placement changes — results identical."""
    planner.configure(enabled=False)
    pages = ColumnarPages.build(_corpus(250, seed=4), PageGeometry(32, 8))
    req = _mk_req({"session.id": "session-01"}, limit=500)
    sp = stage(pages, probe_min_vals=1)  # staged while planner off
    assert sp.staged_dict is not None
    eng = ScanEngine(top_k=1024)
    cq_dev = compile_query(pages.key_dict, pages.val_dict, req,
                           staged_dict=sp.staged_dict)
    assert cq_dev.val_hits is not None
    out_dev = eng.scan_staged(sp, cq_dev)

    _force("host")
    pipeline._COMPILE_CACHE.clear()
    cq_host = compile_query(pages.key_dict, pages.val_dict, req,
                            staged_dict=sp.staged_dict)
    assert cq_host.val_hits is None  # vetoed at compile time
    out_host = eng.scan_staged(sp, cq_host)
    assert out_dev[0] == out_host[0] and out_dev[1] == out_host[1]
    assert np.array_equal(out_dev[2], out_host[2])
    # the compile-site decision landed in the ring with its inputs
    snap = planner.PLANNER.snapshot()
    assert snap["decisions"]["host"] >= 1
    assert any(d["site"] == "compile" and d["target"] == "host"
               for d in snap["recent"])


def test_multiblock_and_coalesced_byte_identical_both_verdicts():
    blocks = _blocks()
    reqs = [_mk_req({"session.id": v}, limit=1000)
            for v in ("session-001", "session-01")]
    planner.configure(enabled=False)
    eng = MultiBlockEngine(top_k=1024)
    batch_off = stack_blocks(blocks, pad_to=32, probe_min_vals=50)
    base = []
    for req in reqs:
        mq = compile_multi(blocks, req, cache_on=batch_off)
        out = eng.scan(batch_off, mq)
        base.append((out[0], out[1],
                     [(m.trace_id, m.start_time_unix_nano)
                      for m in eng.results(batch_off, mq, out[2], out[3])]))

    for verdict in ("device", "host"):
        _force(verdict)
        pipeline._COMPILE_CACHE.clear()
        batch = stack_blocks(blocks, pad_to=32, probe_min_vals=50)
        if verdict == "host":
            assert not batch.staged_dicts  # stage-time veto
        else:
            assert len(batch.staged_dicts) == 3
        mqs = []
        for i, req in enumerate(reqs):
            mq = compile_multi(blocks, req, cache_on=batch)
            out = eng.scan(batch, mq)
            got = (out[0], out[1],
                   [(m.trace_id, m.start_time_unix_nano)
                    for m in eng.results(batch, mq, out[2], out[3])])
            assert got == base[i], (verdict, i)
            mqs.append(mq)
        # coalesced fused dispatch over the same batch, same verdicts
        cq = stack_queries(mqs)
        counts = np.asarray(eng.coalesced_scan_async(batch, cq, 1024)[0])
        for qi in range(len(mqs)):
            assert counts[qi] == base[qi][0], (verdict, qi)


def test_mesh_byte_identical_both_verdicts():
    from tempo_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    blocks = _blocks(n=2, entries=256, small_tail=False)
    req = _mk_req({"session.id": "session-00"}, limit=1000)

    planner.configure(enabled=False)
    eng_off = MultiBlockEngine(top_k=1024)
    batch_off = eng_off.stage(blocks)
    mq_off = compile_multi(blocks, req, cache_on=batch_off)
    out_base = eng_off.scan(batch_off, mq_off)
    ids_base = {m.trace_id for m in eng_off.results(
        batch_off, mq_off, out_base[2], out_base[3])}

    for verdict in ("device", "host"):
        _force(verdict)
        pipeline._COMPILE_CACHE.clear()
        eng = MultiBlockEngine(top_k=1024, mesh=mesh,
                               device_probe_min_vals=50)
        batch = eng.stage(blocks)
        assert bool(batch.staged_dicts) == (verdict == "device")
        mq = compile_multi(blocks, req, cache_on=batch)
        assert (mq.val_hits is not None) == (verdict == "device")
        out = eng.scan(batch, mq)
        assert out[0] == out_base[0] and out[1] == out_base[1]
        ids = {m.trace_id
               for m in eng.results(batch, mq, out[2], out[3])}
        assert ids == ids_base, verdict


def test_dist_search_staged_dict_and_identity():
    """DistributedScanEngine's single-block mesh path stages the
    dictionary value-axis-sharded and yields host-identical results;
    the default threshold (0) keeps its historical host-only behavior."""
    from tempo_tpu.parallel.dist_search import DistributedScanEngine
    from tempo_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    pages = ColumnarPages.build(_corpus(256, seed=5), PageGeometry(32, 8))
    req = _mk_req({"session.id": "session-00"}, limit=1000)

    assert DistributedScanEngine(mesh).stage(pages).staged_dict is None

    planner.configure(enabled=False)
    dist = DistributedScanEngine(mesh, top_k=1024, probe_min_vals=1)
    sp = dist.stage(pages)
    assert sp.staged_dict is not None
    assert sp.staged_dict.mesh is mesh
    cq = compile_query(pages.key_dict, pages.val_dict, req,
                       staged_dict=sp.staged_dict)
    assert cq.val_hits is not None
    out = dist.scan_staged(sp, cq)

    pipeline._COMPILE_CACHE.clear()
    eng = ScanEngine(top_k=1024)
    sp_h = stage(pages, probe_min_vals=0)
    cq_h = compile_query(pages.key_dict, pages.val_dict, req)
    out_h = eng.scan_staged(sp_h, cq_h)
    assert out[0] == out_h[0] and out[1] == out_h[1]
    assert np.array_equal(np.sort(out[2]), np.sort(out_h[2]))


def test_batcher_concurrent_planner_on_identical():
    """Concurrent coalesced searches with the planner choosing device
    serialize to the same bytes as solo planner-off runs."""
    from tempo_tpu.search.batcher import BlockBatcher, ScanJob

    blocks = _blocks(n=2, small_tail=False)

    def jobs():
        out = []
        for i, p in enumerate(blocks):
            out.append(ScanJob(
                key=(f"blk-{i:03d}", 0, p.n_pages), pages_fn=(lambda p=p: p),
                header=dict(p.header), n_pages=p.n_pages,
                n_entries=p.n_entries,
                geometry=(p.header["entries_per_page"],
                          p.header["kv_per_entry"])))
        return out

    reqs = [_mk_req({"session.id": f"session-0{i:02d}"[:11]}, limit=200)
            for i in range(4)]
    planner.configure(enabled=False)
    serial_b = BlockBatcher(coalesce_max_queries=1, device_probe_min_vals=10)
    serial = [serial_b.search(jobs(), r).response().SerializeToString()
              for r in reqs]

    _force("device")
    pipeline._COMPILE_CACHE.clear()
    co_b = BlockBatcher(coalesce_window_s=0.05, coalesce_max_queries=4,
                        device_probe_min_vals=10)
    co_b.search(jobs(), reqs[0])  # warm staging + compile
    barrier = threading.Barrier(len(reqs))
    got = [None] * len(reqs)

    def worker(i):
        barrier.wait()
        got[i] = co_b.search(jobs(), reqs[i]).response().SerializeToString()

    ts = [threading.Thread(target=worker, args=(i,))
          for i in range(len(reqs))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert got == serial


# ---------------------------------------------------------------------------
# planning cost: once per group, not per member/query


def test_plans_once_per_group_and_memoizes_repeats():
    from tempo_tpu.search.batcher import BlockBatcher, ScanJob

    blocks = _blocks(n=2, small_tail=False)
    jobs = [ScanJob(key=(f"blk-{i:03d}", 0, p.n_pages),
                    pages_fn=(lambda p=p: p), header=dict(p.header),
                    n_pages=p.n_pages, n_entries=p.n_entries,
                    geometry=(p.header["entries_per_page"],
                              p.header["kv_per_entry"]))
            for i, p in enumerate(blocks)]
    _force("device")
    b = BlockBatcher(coalesce_max_queries=1, device_probe_min_vals=10)
    req = _mk_req({"session.id": "session-01"}, limit=100)
    b.search(jobs, req)
    first = planner.PLANNER.snapshot()["decisions"]
    # 2 distinct dictionaries: one stage + one compile decision each
    assert first["device"] + first["host"] == 4
    b.search(jobs, req)  # repeat: staged batch + compile cache hit
    again = planner.PLANNER.snapshot()["decisions"]
    assert again == first, "a repeated query over a staged group re-planned"


def test_host_veto_memoized_per_dictionary():
    """Blocks sharing one dictionary get ONE stage-site decision even
    when the verdict is host (a veto produces no staged entry to dedup
    on — the vetoed-fingerprint memo must dedup instead, or a 64-block
    batch books 64 duplicate decisions into the ring and metrics)."""
    from tempo_tpu.search.multiblock import _pack_batch_dicts

    p = _force("host")
    base = _corpus(60, seed=3)
    shared = [ColumnarPages.build(base, PageGeometry(32, 8))
              for _ in range(4)]  # same entries -> same dictionary
    out = _pack_batch_dicts(shared, probe_min_vals=5)
    assert out == {}  # host verdict: nothing staged
    dec = p.snapshot()["decisions"]
    assert dec["host"] == 1, dec  # one shared dict -> one decision


# ---------------------------------------------------------------------------
# cold start / seeding


def test_cold_process_decides_without_crashing():
    """Empty aggregates + seeding enabled: the first decision runs the
    microbenchmark and returns finite costs (no guessing, no crash)."""
    p = planner.configure(enabled=True, seed=True, reset=True)
    d = p.decide_probe(n_vals=10_000_000, dict_bytes=160 << 20,
                       resident=False, site="stage")
    assert d.target in ("host", "device")
    assert 0 < d.predicted_host_s < float("inf")
    assert 0 < d.predicted_device_s < float("inf")
    snap = p.snapshot()
    assert snap["seeded"] is True
    assert snap["seed_ms"] is not None
    # the seed populated every decision-consumed per-byte rate ("scan"
    # is observational — it fills from the first live scan dispatches)
    for kind in planner.SEEDED_KINDS:
        assert snap["cost_model"]["rates"][kind]["observations"] > 0


def test_seed_does_not_double_feed_and_cold_stage_predicts_compile():
    """The seed microbenchmark's own probe dispatch emits a dict_probe
    record + h2d staging observation through the profiler; the listener
    gate must keep those from landing ON TOP of the seed's direct
    updates (contradictory EWMA samples). And a seeded-but-otherwise
    cold process must still predict the first-shape XLA compile for
    stage-site decisions — the first real dictionary WILL pay it."""
    from tempo_tpu.observability import profile

    profile.configure(enabled=True)
    p = planner.configure(enabled=True, seed=True, reset=True)
    d = p.decide_probe(n_vals=10_000_000, dict_bytes=160 << 20,
                       resident=False, site="stage")
    snap = p.snapshot()
    assert snap["seeded"] is True
    # exactly the seed's one direct update per rate — the seed dispatch's
    # profiler record did not double-feed device_probe or h2d
    assert snap["cost_model"]["rates"]["device_probe"]["observations"] == 1
    assert snap["cost_model"]["rates"]["h2d"]["observations"] == 1
    # no real probe has run yet: the stage-site prediction charges the
    # compile cost (the seed's rates deliberately don't clear this)
    assert d.inputs["jit_miss"] is True
    p.observe("device_probe", 0.01, nbytes=800 << 20)  # a real probe
    d2 = p.decide_probe(n_vals=10_000_000, dict_bytes=160 << 20,
                        resident=False, site="stage")
    assert d2.inputs["jit_miss"] is False


def test_cold_process_does_not_stage_huge_dict_blindly():
    """With a relay-slow observed H2D, a non-resident 720 MB dictionary
    must NOT be staged: the staging bytes dominate any probe win."""
    p = planner.configure(enabled=True, seed=False, reset=True)
    p.seed_on_first_use = False
    p.observe("h2d", 1.0, nbytes=50 << 20)       # ~50 MB/s relay
    p.observe("host_probe", 0.35, nbytes=160 << 20)  # PR4's measured 312ms/10M
    p.observe("device_probe", 0.01, nbytes=800 << 20)  # chip-fast probe
    d = p.decide_probe(n_vals=10_000_000, dict_bytes=160 << 20,
                       resident=False, site="stage")
    assert d.target == "host"
    # once resident, the same dictionary flips to the fast device probe
    d2 = p.decide_probe(n_vals=10_000_000, dict_bytes=160 << 20,
                        resident=True, staged_bytes=800 << 20,
                        site="compile")
    assert d2.target == "device"


# ---------------------------------------------------------------------------
# calibration: predicted vs actual, metrics, /debug/planner, offline replay


def test_predicted_vs_actual_resolution():
    p = _force("device")
    fp = b"\xaa" * 32
    d = p.decide_probe(n_vals=1000, dict_bytes=10_000, resident=True,
                       staged_bytes=50_000, fp=fp, site="compile")
    assert d.target == "device" and d.actual_s is None
    p.observe("device_probe", d.predicted_probe_s * 2, nbytes=50_000, fp=fp)
    snap = p.snapshot()
    rec = next(r for r in snap["recent"] if r.get("fp") == fp.hex()[:16])
    assert rec["actual_probe_ms"] > 0
    assert abs(rec["abs_rel_error"] - 0.5) < 0.01  # pred = actual/2
    assert snap["mispredict"]["observations"] == 1


def test_compile_record_resolves_compile_inclusive():
    """A compile-stage dispatch record measures trace+compile+run in one
    wall time; resolving it against the probe-only prediction would book
    ~100% error on every correctly predicted cold-shape compile. The
    resolution must include the decision's predicted compile cost."""
    p = _force("device")
    for _ in range(100):  # converge the compile EWMA to ~0.5s
        p._update("compile", 0.5, 0)
    fp = b"\xbb" * 32
    d = p.decide_probe(n_vals=1000, dict_bytes=10_000, resident=True,
                       staged_bytes=50_000, fp=fp, site="compile",
                       shape_key=("never-seen-shape", 0))
    assert d.target == "device" and d.inputs["jit_miss"]
    assert d.predicted_compile_s > 0.1  # the compile term was charged
    actual_s = d.predicted_probe_s + d.predicted_compile_s  # spot-on
    n = p.ingest_record({
        "mode": "dict_probe",
        "stages_ms": {"compile": actual_s * 1e3},
        "attrs": {"probe_bytes": 50_000, "fp": fp.hex()[:16]},
    })
    assert n >= 1
    rec = next(r for r in p.snapshot()["recent"]
               if r.get("fp") == fp.hex()[:16])
    assert rec["abs_rel_error"] < 0.01  # NOT ~1.0


def test_profiler_listener_feeds_device_rate():
    """A finished dict_probe dispatch record (the profiler's listener
    path) updates the device rate and resolves the pending decision."""
    from tempo_tpu.observability import profile

    p = _force("device")
    profile.configure(enabled=True)
    before = p.snapshot()["cost_model"]["rates"]["device_probe"][
        "observations"]
    pages = ColumnarPages.build(_corpus(150, seed=6), PageGeometry(32, 8))
    sp = stage(pages, probe_min_vals=1)
    cq = compile_query(pages.key_dict, pages.val_dict,
                       _mk_req({"session.id": "session-01"}, limit=20),
                       cache_on=pages, staged_dict=sp.staged_dict)
    assert cq is not None and cq.val_hits is not None
    after = p.snapshot()["cost_model"]["rates"]["device_probe"][
        "observations"]
    assert after > before


def test_debug_planner_endpoint():
    from tempo_tpu.api.http import HTTPApi

    _force("host")
    planner.PLANNER.decide_probe(n_vals=100, dict_bytes=1000,
                                 site="compile")
    api = HTTPApi(app=None)
    code, body = api.handle("GET", "/debug/planner", {}, {})
    assert code == 200
    assert body["enabled"] is True
    assert body["decisions"]["host"] >= 1
    assert body["recent"], "decision ring empty"
    code, body = api.handle("GET", "/debug/planner", {"recent": "0"}, {})
    assert code == 200 and body["recent"] == []
    # gated off with the other /debug routes
    api_off = HTTPApi(app=None, debug_endpoints=False)
    code, body = api_off.handle("GET", "/debug/planner", {}, {})
    assert code == 404


def test_offline_replay_from_profile_snapshot(tmp_path, capsys):
    """scripts/calibrate_offload.py rebuilds the cost model from a
    /debug/profile dump and prints the decision table."""
    snap = {
        "dispatches": 3,
        "aggregates": {
            "host_probe": {"build": {"count": 4, "total_ms": 1200.0,
                                     "mean_ms": 300.0,
                                     "bytes": 4 * (160 << 20)}},
            "dict_probe": {"h2d": {"count": 2, "total_ms": 30000.0,
                                   "mean_ms": 15000.0,
                                   "bytes": 2 * (800 << 20)}},
        },
        "recent": [{
            "mode": "dict_probe",
            "stages_ms": {"build": 0.2, "execute": 12.0},
            "attrs": {"probe_bytes": 800 << 20, "fp": "ab" * 8},
        }],
    }
    p = planner.OffloadPlanner(enabled=True, seed=False)
    n = p.ingest_profile_snapshot(snap)
    assert n >= 3
    # chip-fast probe + slow relay: big non-resident dict stays host,
    # resident flips device
    d_cold = p.decide_probe(n_vals=10_000_000, dict_bytes=160 << 20,
                            resident=False, site="offline")
    d_warm = p.decide_probe(n_vals=10_000_000, dict_bytes=160 << 20,
                            resident=True, staged_bytes=800 << 20,
                            site="offline")
    assert d_cold.target == "host" and d_warm.target == "device"

    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "calibrate_offload.py")
    spec = importlib.util.spec_from_file_location("calibrate_offload", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    dump = tmp_path / "profile.json"
    dump.write_text(json.dumps(snap))
    assert mod.main([str(dump), "--recent", "2"]) == 0
    out = capsys.readouterr().out
    assert "decision table" in out and "10000000" in out
    assert "host" in out and "device" in out


def test_planner_metrics_documented_and_incremented():
    from tempo_tpu.observability import metrics as obs

    p = _force("host")
    before = obs.offload_decisions.value(target="host", site="compile")
    p.decide_probe(n_vals=100, dict_bytes=1000, site="compile")
    assert obs.offload_decisions.value(target="host",
                                       site="compile") == before + 1


# ---------------------------------------------------------------------------
# satellite: value-memoized device scalars


def test_device_scalar_params_shared_across_queries():
    """Two distinct compiled queries with the same (default) bounds must
    reuse the SAME device scalar arrays — the per-query scalar H2D puts
    were measured relay tax (engine.py docstring)."""
    from tempo_tpu.search.engine import device_scalar

    pages = ColumnarPages.build(_corpus(50, seed=7), PageGeometry(32, 8))
    cq1 = compile_query(pages.key_dict, pages.val_dict,
                        _mk_req({"session.id": "session-00"}, limit=20))
    cq2 = compile_query(pages.key_dict, pages.val_dict,
                        _mk_req({"svc": "frontend"}, limit=20))
    p1 = ScanEngine.query_device_params(cq1)
    p2 = ScanEngine.query_device_params(cq2)
    for i in (2, 3, 4, 5):  # dur_lo, dur_hi, win_start, win_end
        assert p1[i] is p2[i]
    assert device_scalar(12345) is device_scalar(12345)
    # cached params still yield correct scans
    eng = ScanEngine(top_k=64)
    sp = stage(pages, probe_min_vals=0)
    c1 = eng.scan_staged(sp, cq1)[0]
    c2 = eng.scan_staged(sp, cq2)[0]
    assert c1 >= 0 and c2 >= 0
