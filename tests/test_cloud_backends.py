"""Cloud backend conformance: S3 / GCS / Azure clients against in-process
mock object stores that verify auth on every request (the reference tests
the same surface against minio / fake-gcs-server / azurite —
integration/e2e/backend/)."""

import pytest

from tempo_tpu.backend import BlockMeta, BackendError, DoesNotExist
from tempo_tpu.backend.s3 import S3Backend
from tempo_tpu.backend.gcs import GCSBackend
from tempo_tpu.backend.azure import AzureBackend
from tempo_tpu.db import TempoDB, TempoDBConfig

from tests.mock_object_stores import (
    start, MockS3Handler, MockGCSHandler, MockAzureHandler,
)
from tests.test_db import _ingest

AZ_KEY = "c2VjcmV0LWtleS1mb3ItdGVzdHM="  # base64("secret-key-for-tests")


@pytest.fixture(scope="module")
def s3_server():
    srv, ep = start(MockS3Handler, access_key="AKIATEST", secret_key="s3cr3t")
    yield srv, ep
    srv.shutdown()


@pytest.fixture(scope="module")
def gcs_server():
    srv, ep = start(MockGCSHandler, token="tok-123")
    yield srv, ep
    srv.shutdown()


@pytest.fixture(scope="module")
def azure_server():
    srv, ep = start(MockAzureHandler, account="testacct", key=AZ_KEY)
    yield srv, ep
    srv.shutdown()


@pytest.fixture(params=["s3", "gcs", "azure"])
def cloud_backend(request, s3_server, gcs_server, azure_server):
    if request.param == "s3":
        srv, ep = s3_server
        be = S3Backend(bucket="tempo", endpoint=ep, access_key="AKIATEST",
                       secret_key="s3cr3t", prefix="traces", retries=1)
    elif request.param == "gcs":
        srv, ep = gcs_server
        be = GCSBackend(bucket="tempo", endpoint=ep, token="tok-123",
                        prefix="traces", retries=1)
    else:
        srv, ep = azure_server
        be = AzureBackend(container="tempo", account="testacct", key=AZ_KEY,
                          endpoint=ep, prefix="traces", retries=1)
    srv.store.clear()
    return be


def test_roundtrip(cloud_backend):
    be = cloud_backend
    be.write("t1", "blk1", "data", b"hello world")
    assert be.read("t1", "blk1", "data") == b"hello world"
    assert be.read_range("t1", "blk1", "data", 6, 5) == b"world"


def test_missing_raises(cloud_backend):
    with pytest.raises(DoesNotExist):
        cloud_backend.read("t1", "blk1", "nope")
    with pytest.raises(DoesNotExist):
        cloud_backend.read_range("t1", "blk1", "nope", 0, 1)


def test_delete(cloud_backend):
    be = cloud_backend
    be.write("t1", "blk1", "data", b"x")
    be.delete("t1", "blk1", "data")
    with pytest.raises(DoesNotExist):
        be.read("t1", "blk1", "data")


def test_listing(cloud_backend):
    be = cloud_backend
    be.write("t1", "blk1", "data", b"a")
    be.write("t1", "blk1", "index", b"b")
    be.write("t1", "blk2", "data", b"c")
    be.write("t2", "blk3", "data", b"d")
    be.write("t1", None, "index.json.gz", b"idx")  # tenant-level object
    assert be.list_tenants() == ["t1", "t2"]
    assert be.list_blocks("t1") == ["blk1", "blk2"]
    assert set(be._block_objects("t1", "blk1")) == {"data", "index"}


def test_meta_and_compaction_cycle(cloud_backend):
    be = cloud_backend
    m = BlockMeta(tenant_id="t1", total_objects=7)
    be.write_block_meta(m)
    got = be.read_block_meta("t1", m.block_id)
    assert got.total_objects == 7
    be.write("t1", m.block_id, "data", b"payload")
    be.mark_compacted(m)
    with pytest.raises(DoesNotExist):
        be.read_block_meta("t1", m.block_id)
    assert be.read_compacted_meta("t1", m.block_id).meta.block_id == m.block_id
    be.clear_block("t1", m.block_id)
    with pytest.raises(DoesNotExist):
        be.read("t1", m.block_id, "data")


def test_s3_bad_credentials_rejected(s3_server):
    _, ep = s3_server
    be = S3Backend(bucket="tempo", endpoint=ep, access_key="AKIATEST",
                   secret_key="WRONG", retries=0)
    with pytest.raises(BackendError):
        be.write("t1", "b", "data", b"x")


def test_azure_bad_key_rejected(azure_server):
    _, ep = azure_server
    be = AzureBackend(container="tempo", account="testacct",
                      key="d3Jvbmcta2V5", endpoint=ep, retries=0)
    with pytest.raises(BackendError):
        be.write("t1", "b", "data", b"x")


def test_gcs_bad_token_rejected(gcs_server):
    _, ep = gcs_server
    be = GCSBackend(bucket="tempo", endpoint=ep, token="nope", retries=0)
    with pytest.raises(BackendError):
        be.write("t1", "b", "data", b"x")


def test_tempodb_end_to_end_on_s3(tmp_path, s3_server):
    """Full write→complete→find→search cycle with S3 as the only durable
    store — the reference's integration/e2e backend matrix, in-process."""
    srv, ep = s3_server
    srv.store.clear()
    be = S3Backend(bucket="tempo", endpoint=ep, access_key="AKIATEST",
                   secret_key="s3cr3t", prefix="single-tenant")
    db = TempoDB(be, str(tmp_path / "wal"), TempoDBConfig())
    meta, traces = _ingest(db, "t1", 40)
    db.poll()
    tid = sorted(traces)[0]
    obj, failed = db.find_trace_by_id("t1", tid)
    assert obj is not None and failed == 0
    # the mock store now holds the whole block: data+index+meta+blooms+search
    assert any(k.endswith("meta.json") for k in srv.store)
    assert any(k.endswith("/search") for k in srv.store)
