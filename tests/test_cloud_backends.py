"""Cloud backend conformance: S3 / GCS / Azure clients against in-process
mock object stores that verify auth on every request (the reference tests
the same surface against minio / fake-gcs-server / azurite —
integration/e2e/backend/)."""

import pytest

from tempo_tpu.backend import BlockMeta, BackendError, DoesNotExist
from tempo_tpu.backend.s3 import S3Backend
from tempo_tpu.backend.gcs import GCSBackend
from tempo_tpu.backend.azure import AzureBackend
from tempo_tpu.db import TempoDB, TempoDBConfig

from tests.mock_object_stores import (
    start, MockS3Handler, MockGCSHandler, MockAzureHandler,
)
from tests.test_db import _ingest

AZ_KEY = "c2VjcmV0LWtleS1mb3ItdGVzdHM="  # base64("secret-key-for-tests")


@pytest.fixture(scope="module")
def s3_server():
    srv, ep = start(MockS3Handler, access_key="AKIATEST", secret_key="s3cr3t")
    yield srv, ep
    srv.shutdown()


@pytest.fixture(scope="module")
def gcs_server():
    srv, ep = start(MockGCSHandler, token="tok-123")
    yield srv, ep
    srv.shutdown()


@pytest.fixture(scope="module")
def azure_server():
    srv, ep = start(MockAzureHandler, account="testacct", key=AZ_KEY)
    yield srv, ep
    srv.shutdown()


@pytest.fixture(params=["s3", "gcs", "azure"])
def cloud_backend(request, s3_server, gcs_server, azure_server):
    if request.param == "s3":
        srv, ep = s3_server
        be = S3Backend(bucket="tempo", endpoint=ep, access_key="AKIATEST",
                       secret_key="s3cr3t", prefix="traces", retries=1)
    elif request.param == "gcs":
        srv, ep = gcs_server
        be = GCSBackend(bucket="tempo", endpoint=ep, token="tok-123",
                        prefix="traces", retries=1)
    else:
        srv, ep = azure_server
        be = AzureBackend(container="tempo", account="testacct", key=AZ_KEY,
                          endpoint=ep, prefix="traces", retries=1)
    srv.store.clear()
    return be


def test_roundtrip(cloud_backend):
    be = cloud_backend
    be.write("t1", "blk1", "data", b"hello world")
    assert be.read("t1", "blk1", "data") == b"hello world"
    assert be.read_range("t1", "blk1", "data", 6, 5) == b"world"


def test_missing_raises(cloud_backend):
    with pytest.raises(DoesNotExist):
        cloud_backend.read("t1", "blk1", "nope")
    with pytest.raises(DoesNotExist):
        cloud_backend.read_range("t1", "blk1", "nope", 0, 1)


def test_delete(cloud_backend):
    be = cloud_backend
    be.write("t1", "blk1", "data", b"x")
    be.delete("t1", "blk1", "data")
    with pytest.raises(DoesNotExist):
        be.read("t1", "blk1", "data")


def test_listing(cloud_backend):
    be = cloud_backend
    be.write("t1", "blk1", "data", b"a")
    be.write("t1", "blk1", "index", b"b")
    be.write("t1", "blk2", "data", b"c")
    be.write("t2", "blk3", "data", b"d")
    be.write("t1", None, "index.json.gz", b"idx")  # tenant-level object
    assert be.list_tenants() == ["t1", "t2"]
    assert be.list_blocks("t1") == ["blk1", "blk2"]
    assert set(be._block_objects("t1", "blk1")) == {"data", "index"}


def test_meta_and_compaction_cycle(cloud_backend):
    be = cloud_backend
    m = BlockMeta(tenant_id="t1", total_objects=7)
    be.write_block_meta(m)
    got = be.read_block_meta("t1", m.block_id)
    assert got.total_objects == 7
    be.write("t1", m.block_id, "data", b"payload")
    be.mark_compacted(m)
    with pytest.raises(DoesNotExist):
        be.read_block_meta("t1", m.block_id)
    assert be.read_compacted_meta("t1", m.block_id).meta.block_id == m.block_id
    be.clear_block("t1", m.block_id)
    with pytest.raises(DoesNotExist):
        be.read("t1", m.block_id, "data")


def test_s3_bad_credentials_rejected(s3_server):
    _, ep = s3_server
    be = S3Backend(bucket="tempo", endpoint=ep, access_key="AKIATEST",
                   secret_key="WRONG", retries=0)
    with pytest.raises(BackendError):
        be.write("t1", "b", "data", b"x")


def test_azure_bad_key_rejected(azure_server):
    _, ep = azure_server
    be = AzureBackend(container="tempo", account="testacct",
                      key="d3Jvbmcta2V5", endpoint=ep, retries=0)
    with pytest.raises(BackendError):
        be.write("t1", "b", "data", b"x")


def test_gcs_bad_token_rejected(gcs_server):
    _, ep = gcs_server
    be = GCSBackend(bucket="tempo", endpoint=ep, token="nope", retries=0)
    with pytest.raises(BackendError):
        be.write("t1", "b", "data", b"x")


def test_tempodb_end_to_end_on_s3(tmp_path, s3_server):
    """Full write→complete→find→search cycle with S3 as the only durable
    store — the reference's integration/e2e backend matrix, in-process."""
    srv, ep = s3_server
    srv.store.clear()
    be = S3Backend(bucket="tempo", endpoint=ep, access_key="AKIATEST",
                   secret_key="s3cr3t", prefix="single-tenant")
    db = TempoDB(be, str(tmp_path / "wal"), TempoDBConfig())
    meta, traces = _ingest(db, "t1", 40)
    db.poll()
    tid = sorted(traces)[0]
    obj, failed = db.find_trace_by_id("t1", tid)
    assert obj is not None and failed == 0
    # the mock store now holds the whole block: data+index+meta+blooms+search
    assert any(k.endswith("meta.json") for k in srv.store)
    assert any(k.endswith("/search") for k in srv.store)


# ---- round 2: streaming append (multipart / resumable / block list) ----

def test_append_roundtrip(cloud_backend):
    """Parts of assorted sizes stream through the native append protocol
    and read back byte-identical; the object is invisible until close."""
    be = cloud_backend
    parts = [b"a" * 10, b"b" * (300 << 10), b"c" * (6 << 20), b"d" * 7, b""]
    tracker = None
    for p in parts:
        tracker = be.append("t1", "blk", "data", tracker, p)
    be.close_append("t1", "blk", "data", tracker)
    got = be.read("t1", "blk", "data")
    want = b"".join(parts)
    assert got == want
    # ranged reads work over the assembled object
    assert be.read_range("t1", "blk", "data", 5, 20) == want[5:25]


def test_append_empty_object(cloud_backend):
    be = cloud_backend
    tracker = be.append("t1", "blk0", "data", None, b"")
    be.close_append("t1", "blk0", "data", tracker)
    assert be.read("t1", "blk0", "data") == b""


def test_append_large_block_via_streaming_block(cloud_backend):
    """StreamingBlock with a backend flushes every flush_size bytes and
    produces a block identical to the buffered path."""
    import io
    from tempo_tpu.encoding.v2 import BackendBlock, StreamingBlock

    objs = [(bytes([i]) * 16, bytes([i]) * 4096) for i in range(64)]

    m1 = BlockMeta(tenant_id="t1", encoding="none")
    sb1 = StreamingBlock(m1, page_size=4096, backend=cloud_backend,
                         flush_size=16 << 10)  # tiny flush -> many parts
    for oid, data in objs:
        sb1.add_object(oid, data)
    out1 = sb1.complete()

    m2 = BlockMeta(tenant_id="t1", encoding="none")
    sb2 = StreamingBlock(m2, page_size=4096)  # buffered path
    for oid, data in objs:
        sb2.add_object(oid, data)
    out2 = sb2.complete(cloud_backend)

    d1 = cloud_backend.read("t1", out1.block_id, "data")
    d2 = cloud_backend.read("t1", out2.block_id, "data")
    assert d1 == d2 and out1.size == out2.size == len(d1)
    # both blocks serve identical lookups
    for oid, data in objs[::7]:
        assert BackendBlock(cloud_backend, out1).find_by_id(oid) == data
        assert BackendBlock(cloud_backend, out2).find_by_id(oid) == data


def test_abort_append_releases_pending_upload(s3_server, gcs_server):
    """abort_append must release server-side upload state (S3 pending
    multipart uploads bill until aborted; GCS sessions linger a week) and
    leave the object invisible (ADVICE r3: failed completions previously
    orphaned one upload per retry attempt)."""
    srv, ep = s3_server
    be = S3Backend(bucket="tempo", endpoint=ep, access_key="AKIATEST",
                   secret_key="s3cr3t", prefix="traces", retries=1)
    tracker = be.append("t1", "blka", "data", None, b"x" * (6 << 20))
    assert getattr(srv, "uploads", {})  # pending multipart exists
    be.abort_append("t1", "blka", "data", tracker)
    assert not srv.uploads
    with pytest.raises(DoesNotExist):
        be.read("t1", "blka", "data")

    srv, ep = gcs_server
    be = GCSBackend(bucket="tempo", endpoint=ep, token="tok-123",
                    prefix="traces", retries=1)
    tracker = be.append("t1", "blka", "data", None, b"y" * (512 << 10))
    assert getattr(srv, "sessions", {})
    be.abort_append("t1", "blka", "data", tracker)
    assert not srv.sessions
    with pytest.raises(DoesNotExist):
        be.read("t1", "blka", "data")


def test_failed_streaming_completion_aborts_append(tmp_path):
    """A completion that dies after streaming began must abort its append:
    no hidden temp files accumulate in the block dir across retries."""
    import os
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.backend.types import NAME_INDEX

    be = LocalBackend(str(tmp_path / "blocks"))
    db = TempoDB(be, str(tmp_path / "wal"),
                 TempoDBConfig(block_encoding="none",
                               block_page_size=8 << 10,
                               complete_flush_bytes=16 << 10))
    real_write = be.write

    def poisoned(tenant, block_id, name, data):
        if name == NAME_INDEX:
            raise OSError("flake")  # dies AFTER the data stream finished
        return real_write(tenant, block_id, name, data)

    be.write = poisoned
    objects = [(bytes([i]) * 16, os.urandom(16 << 10), 0, 0)
               for i in range(12)]
    for attempt in range(3):
        with pytest.raises(OSError):
            db.write_block_direct("t1", objects)
    be.write = real_write
    # no orphaned append temp files anywhere under the tenant dir
    stray = [os.path.join(r, f)
             for r, _, fs in os.walk(str(tmp_path / "blocks"))
             for f in fs if ".append." in f]
    assert stray == [], stray
    # and no committed-but-metaless objects either: each attempt minted a
    # fresh block id whose streamed `data` object committed before the
    # index write failed — abort() must have deleted it, or retention
    # (blocklist-driven) would never reclaim it
    leftovers = [os.path.join(r, f)
                 for r, _, fs in os.walk(str(tmp_path / "blocks"))
                 for f in fs]
    assert leftovers == [], leftovers


def test_ambiguous_meta_failure_keeps_block_objects(tmp_path):
    """If the meta write fails AMBIGUOUSLY (meta may be durably stored
    server-side) and the meta delete also fails, abort must NOT delete
    data/index — a visible meta pointing at deleted objects is worse than
    orphaned garbage (code-review r3 finding)."""
    import os
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.backend.types import NAME_META, BlockMeta
    from tempo_tpu.encoding.v2 import StreamingBlock

    be = LocalBackend(str(tmp_path / "blocks"))
    real_write = be.write
    real_delete = be.delete

    def meta_write_times_out(tenant, block_id, name, data):
        real_write(tenant, block_id, name, data)  # server stored it...
        if name == NAME_META:
            raise OSError("client timeout")  # ...but the client never knew

    be.write = meta_write_times_out
    be.delete = lambda *a: (_ for _ in ()).throw(OSError("down"))
    m = BlockMeta(tenant_id="t1", encoding="none")
    sb = StreamingBlock(m, page_size=4096)
    sb.add_object(b"\x01" * 16, b"x" * 8192)
    with pytest.raises(OSError):
        sb.complete(be)
    sb.abort()
    be.write, be.delete = real_write, real_delete
    # data/index survived: the (durably stored) meta still points at a
    # whole block
    names = set(os.listdir(str(tmp_path / "blocks" / "t1" / m.block_id)))
    assert "data" in names and "meta.json" in names

    # when the meta delete WORKS, abort reclaims everything
    be2 = LocalBackend(str(tmp_path / "blocks2"))
    be2.write = lambda t, b, n, d, w=be2.write: (
        (_ for _ in ()).throw(OSError("boom")) if n == NAME_META else w(t, b, n, d))
    m2 = BlockMeta(tenant_id="t1", encoding="none")
    sb2 = StreamingBlock(m2, page_size=4096)
    sb2.add_object(b"\x02" * 16, b"y" * 8192)
    with pytest.raises(OSError):
        sb2.complete(be2)
    sb2.abort()
    assert not os.path.exists(str(tmp_path / "blocks2" / "t1" / m2.block_id))
