"""Write-path telemetry end-to-end: the push→searchable stage record,
freshness gauges, backlog visibility, the canary, the slow-flush log,
the WAL-replay metrics, and the telemetry-off noop contract
(observability/ingest_telemetry.py + the instrumented distributor /
ingester / poller / compactor sites)."""

import json
import logging
import threading
import time

import pytest

from tempo_tpu.modules import App, AppConfig
from tempo_tpu.observability import ingest_telemetry
from tempo_tpu.observability import metrics as obs
from tempo_tpu.observability.ingest_telemetry import (
    TELEMETRY,
    IngestCanary,
)
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.utils.test_data import make_trace


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Process-global sink: every test starts from a known config and
    leaves no pending flush→poll pairs for its neighbors."""
    ingest_telemetry.configure(enabled=True, slow_flush_log_s=30.0)
    TELEMETRY.reset()
    TELEMETRY.canary = None
    yield
    ingest_telemetry.configure(enabled=True, slow_flush_log_s=30.0)
    TELEMETRY.reset()
    TELEMETRY.canary = None


def _app(tmp_path, **kw):
    return App(AppConfig(wal_dir=str(tmp_path / "wal"), **kw))


def _now_batch(tag_value: str = ""):
    """One single-span trace stamped NOW (the freshness gauge derives
    from block end_times, so 2020-epoch test data would read as years
    of staleness)."""
    import os

    from tempo_tpu import tempopb

    rs = tempopb.ResourceSpans()
    kv = rs.resource.attributes.add()
    kv.key = "service.name"
    kv.value.string_value = "svc-now"
    ss = rs.scope_spans.add()
    span = ss.spans.add()
    span.trace_id = os.urandom(16)
    span.span_id = os.urandom(8)
    span.name = "op-now"
    now_ns = time.time_ns()
    span.start_time_unix_nano = now_ns - 5_000_000
    span.end_time_unix_nano = now_ns
    if tag_value:
        kv = span.attributes.add()
        kv.key = "probe.id"
        kv.value.string_value = tag_value
    return rs


def _stage_count(stage: str) -> int:
    h = obs.ingest_stage_seconds
    with h._lock:
        counts = h._counts.get((("stage", stage),))
        return sum(counts) if counts else 0


def _hist_count(hist, **labels) -> int:
    with hist._lock:
        counts = hist._counts.get(hist._key(labels))
        return sum(counts) if counts else 0


STAGES = ("push_ack", "live_cut", "block_cut", "flush", "flush_write",
          "poll_visible", "push_to_searchable")


# ---- the full pipeline record ----

def test_stage_histograms_populate_push_to_searchable(tmp_path):
    before = {s: _stage_count(s) for s in STAGES}
    flushes = _hist_count(obs.flush_duration_seconds, tenant="t1")
    app = _app(tmp_path)
    for _ in range(4):
        app.push("t1", [_now_batch()])
    app.flush_tick(force=True)
    app.poll_tick()
    # every stage of push -> cut -> complete -> flush -> poll observed
    for s in STAGES:
        assert _stage_count(s) > before[s], f"stage {s} not observed"
    assert _hist_count(obs.flush_duration_seconds, tenant="t1") > flushes
    # backlog gauges: everything flushed, nothing waiting
    assert obs.flush_queue_length.value(tenant="t1") == 0
    assert obs.oldest_unflushed.value(tenant="t1") == 0
    assert obs.blocklist_length.value(tenant="t1") >= 1


def test_freshness_gauge_small_after_poll_of_fresh_data(tmp_path):
    app = _app(tmp_path)
    app.push("fresh-t", [_now_batch()])
    app.flush_tick(force=True)
    app.poll_tick()
    # spans were stamped NOW: the polled freshness must be seconds, and
    # the gauge must have DECREASED from whatever staler state a prior
    # poll (other tests, earlier blocks) left behind
    v = obs.search_freshness.value(tenant="fresh-t")
    assert 0 <= v < 60
    # a later poll without new data ages the gauge monotonically
    time.sleep(0.02)
    app.poll_tick()
    assert obs.search_freshness.value(tenant="fresh-t") >= v


def test_oldest_unflushed_tracks_backlog_then_resets(tmp_path):
    app = _app(tmp_path)
    app.push("lag-t", [_now_batch()])
    ing = app.ingesters["ingester-0"]
    # sweep WITHOUT force: the trace stays live (idle < 10s) — the
    # backlog gauge must show its age (gauge precision is 1ms, so give
    # the trace measurable age first)
    time.sleep(0.02)
    ing.sweep()
    assert obs.flush_queue_length.value(tenant="lag-t") == 0
    lag = obs.oldest_unflushed.value(tenant="lag-t")
    assert 0 < lag < 60
    app.flush_tick(force=True)
    assert obs.oldest_unflushed.value(tenant="lag-t") == 0


def test_push_ack_not_recorded_when_disabled(tmp_path):
    app = _app(tmp_path, ingest_telemetry_enabled=False)
    before = {s: _stage_count(s) for s in STAGES}
    for _ in range(3):
        app.push("off-t", [_now_batch()])
    app.flush_tick(force=True)
    app.poll_tick()
    for s in STAGES:
        assert _stage_count(s) == before[s], f"stage {s} leaked while off"


def test_telemetry_off_is_byte_identical_on_the_wal(tmp_path):
    """The noop contract: identical pushes produce identical WAL bytes
    with telemetry on vs off (the bench freshness phase asserts the
    same over the full App; this is the tier-1 fast version)."""

    def wal_bytes(enabled: bool, sub: str) -> bytes:
        ingest_telemetry.configure(enabled=enabled)
        app = App(AppConfig(wal_dir=str(tmp_path / sub),
                            ingest_telemetry_enabled=enabled))
        for i in range(6):
            tr = make_trace(bytes([i + 1]) * 16, seed=i)
            app.push("noop", list(tr.batches))
        inst = app.ingesters["ingester-0"].instance("noop")
        inst.cut_complete_traces(force=True)
        with open(inst.head.path, "rb") as f:
            head = f.read()
        with open(inst.head.path + ".search", "rb") as f:
            return head + b"\x00|\x00" + f.read()

    on = wal_bytes(True, "on")
    off = wal_bytes(False, "off")
    assert on == off
    assert len(on) > 100  # the comparison compared real data


# ---- flush failure / retry visibility ----

def test_flush_retry_counter_by_attempt_bucket(tmp_path, monkeypatch):
    app = _app(tmp_path)
    app.push("rt", [_now_batch()])
    ing = app.ingesters["ingester-0"]
    inst = ing.instance("rt")
    inst.cut_complete_traces(force=True)
    inst.cut_block_if_ready(force=True)
    before1 = obs.flush_retries.value(attempt="1")
    before2 = obs.flush_retries.value(attempt="2")
    boom = RuntimeError("backend down")
    monkeypatch.setattr(ing.db, "complete_block",
                        lambda *a, **k: (_ for _ in ()).throw(boom))
    with pytest.raises(RuntimeError):
        inst.complete_one(ignore_backoff=True)
    with pytest.raises(RuntimeError):
        inst.complete_one(ignore_backoff=True)
    assert obs.flush_retries.value(attempt="1") == before1 + 1
    assert obs.flush_retries.value(attempt="2") == before2 + 1
    # the block is still completing (not lost), and recovers
    monkeypatch.undo()
    assert inst.complete_one(ignore_backoff=True) is not None
    assert not inst.completing


def test_slow_flush_log_line_is_pure_json(tmp_path, caplog):
    before = obs.slow_flushes.value(tenant="slow-t")
    # threshold via the App config (App construction re-configures the
    # process sink, so a bare configure() before it would be undone)
    app = _app(tmp_path, ingest_slow_flush_log_s=1e-9)
    app.push("slow-t", [_now_batch()])
    with caplog.at_level(logging.WARNING, logger="tempo_tpu.slowflush"):
        app.flush_tick(force=True)
    lines = [r for r in caplog.records if r.name == "tempo_tpu.slowflush"]
    assert lines, "no slow-flush line emitted"
    doc = json.loads(lines[0].getMessage())
    assert doc["msg"] == "slow flush"
    assert doc["tenant"] == "slow-t"
    assert doc["duration_s"] >= 0
    assert doc["objects"] >= 1
    assert "block_id" in doc and "attempts" in doc
    assert obs.slow_flushes.value(tenant="slow-t") > before
    # the ring for /debug/ingest carries the same entry
    assert any(e["tenant"] == "slow-t"
               for e in TELEMETRY.debug_snapshot()["slow_flushes"])


# ---- WAL replay attribution ----

def test_wal_replay_is_timed_and_exported(tmp_path):
    app = _app(tmp_path)
    for i in range(3):
        tr = make_trace(random_trace_id(), seed=i)
        app.push("replay-t", list(tr.batches))
    inst = app.ingesters["ingester-0"].instance("replay-t")
    inst.cut_complete_traces(force=True)
    assert len(inst.head) > 0  # data sits in the WAL, unflushed
    # a new process over the same WAL dir replays it
    app2 = _app(tmp_path)
    ing2 = app2.ingesters["ingester-0"]
    assert ing2.replayed_blocks >= 1
    stats = ing2.db.wal.last_replay
    assert stats["blocks"] >= 1
    assert stats["bytes"] > 0
    assert stats["duration_s"] > 0
    assert obs.wal_replayed_blocks.value() >= 1
    assert obs.wal_replayed_bytes.value() > 0
    assert obs.wal_replay_seconds.value() > 0
    assert TELEMETRY.debug_snapshot()["wal_replay"]["blocks"] >= 1
    # replayed blocks flush on the next sweep
    assert len(app2.flush_tick(force=True)) >= 1


def test_replayed_backlog_ages_instead_of_reading_zero(tmp_path):
    """Replayed WAL blocks carry no push stamp — the oldest-unflushed
    gauge must fall back to their enqueue (replay) time so a wedged
    post-restart backlog ages instead of reporting 'fully flushed'
    (review r3)."""
    app = _app(tmp_path)
    app.push("rb-t", [_now_batch()])
    inst = app.ingesters["ingester-0"].instance("rb-t")
    inst.cut_complete_traces(force=True)
    app2 = _app(tmp_path)  # replays; nobody flushes (wedged restart)
    ing2 = app2.ingesters["ingester-0"]
    assert ing2.replayed_blocks >= 1
    time.sleep(0.02)
    ing2._publish_queue_state()
    assert obs.flush_queue_length.value(tenant="rb-t") >= 1
    assert obs.oldest_unflushed.value(tenant="rb-t") > 0


# ---- canary ----

def _ticking(app, stop, flush_every=0.05):
    def body():
        while not stop.wait(flush_every):
            app.flush_tick(force=True)
            app.poll_tick()
    t = threading.Thread(target=body, daemon=True)
    t.start()
    return t


def test_canary_round_trip_measures_freshness(tmp_path):
    app = _app(tmp_path)
    stop = threading.Event()
    t = _ticking(app, stop)
    try:
        can = IngestCanary(app.push, app.reader_db.search,
                           tenant="canary-ok", poll_step_s=0.02)
        f = can.probe_once(timeout_s=60.0)
    finally:
        stop.set()
        t.join(timeout=10.0)
    assert f is not None and f > 0
    assert can.failures == 0
    assert can.state()["last_freshness_s"] == round(f, 3)
    assert obs.canary_freshness.value() == round(f, 3)
    # the canary block went through the real pipeline: freshness gauge
    # exists for its tenant too
    assert obs.search_freshness.value(tenant="canary-ok") < 60


def test_canary_failure_counter_fires_when_pipeline_is_wedged(tmp_path):
    app = _app(tmp_path)  # nobody drives flush/poll: a wedged pipeline
    before = obs.canary_failures.value()
    can = IngestCanary(app.push, app.reader_db.search,
                       tenant="canary-wedge", poll_step_s=0.02)
    f = can.probe_once(timeout_s=0.3)
    assert f is None
    assert can.failures == 1
    assert obs.canary_failures.value() == before + 1
    assert "not searchable" in can.state()["last_error"]


def test_canary_lifecycle_via_app_config(tmp_path):
    app = _app(tmp_path, ingest_canary_enabled=True,
               ingest_canary_interval_s=3600.0)
    try:
        assert app.canary is not None
        assert TELEMETRY.canary is app.canary
        app.run_maintenance()
        assert app.canary.state()["running"]
    finally:
        app.shutdown()
    assert not app.canary.state()["running"]


# ---- surfaces ----

def test_status_and_debug_ingest_surfaces(tmp_path):
    from tempo_tpu.api.http import HTTPApi

    app = _app(tmp_path)
    app.push("surf-t", [_now_batch()])
    app.flush_tick(force=True)
    app.poll_tick()
    app.compaction_tick()
    api = HTTPApi(app)
    code, status = api.handle("GET", "/status", {}, {})
    assert code == 200
    blk = status["ingest"]
    assert "surf-t" in blk["freshness_seconds"]
    assert blk["oldest_unflushed_seconds"]["surf-t"] == 0
    assert blk["last_poll_age_s"] is not None
    code, dbg = api.handle("GET", "/debug/ingest", {}, {})
    assert code == 200
    json.dumps(dbg)  # a debug page must always be JSON-serializable
    assert dbg["enabled"] is True
    assert dbg["queues"]["surf-t"]["queue_length"] == 0
    assert dbg["last_flush"]["surf-t"]["objects"] >= 1
    assert dbg["last_poll"]["blocks"] >= 1
    # live view: this app runs ingesters in-process
    assert dbg["live"]["surf-t"]["live_traces"] == 0
    assert dbg["live"]["surf-t"]["recent_blocks"] >= 1


def test_compaction_backlog_and_run_metrics(tmp_path):
    app = _app(tmp_path)
    # two same-window blocks -> one compactable group
    for i in range(2):
        app.push("comp-t", [_now_batch()])
        app.flush_tick(force=True)
    app.poll_tick()
    runs_before = _hist_count(obs.compaction_duration_seconds)
    app.compaction_tick()
    assert _hist_count(obs.compaction_duration_seconds) > runs_before
    # backlog gauge was set (to the pre-run backlog) for the tenant
    assert obs.compaction_outstanding_bytes.value(tenant="comp-t") > 0


def test_freshness_gauge_removed_when_tenant_vanishes():
    """A tenant that disappears from a poll must STOP exporting its
    last freshness value — a frozen 'fresh' reading for a tenant whose
    searchable data is gone is worse than no series (review r1)."""
    from tempo_tpu.backend.types import BlockMeta

    m = BlockMeta(tenant_id="ghost-t", end_time=int(time.time()))
    TELEMETRY.record_poll(0.01, {"ghost-t": [m]})
    assert obs.search_freshness.value(tenant="ghost-t") < 60
    with obs.search_freshness._lock:
        assert (("tenant", "ghost-t"),) in obs.search_freshness._series
    TELEMETRY.record_poll(0.01, {})  # tenant gone from the next poll
    with obs.search_freshness._lock:
        assert (("tenant", "ghost-t"),) not in obs.search_freshness._series
    with obs.blocklist_length._lock:
        assert (("tenant", "ghost-t"),) not in obs.blocklist_length._series
    assert "ghost-t" not in TELEMETRY.status()["freshness_seconds"]


def test_blocklist_index_age_gauge(tmp_backend_dir, tmp_wal_dir):
    """A reader (non-builder) poller consuming a builder-written tenant
    index must export the index's age."""
    from tempo_tpu.backend import open_backend
    from tempo_tpu.db import TempoDB, TempoDBConfig

    backend = open_backend({"backend": "local",
                            "local": {"path": tmp_backend_dir}})
    writer = TempoDB(backend, tmp_wal_dir + "/w", TempoDBConfig())
    writer.write_block_direct(
        "idx-t", [(bytes([7]) * 16, b"obj-bytes", 10, 20)])
    writer.poll()  # builder: writes the tenant index
    reader = TempoDB(backend, tmp_wal_dir + "/r", TempoDBConfig(
        tenant_index_builder=False))
    reader.poll()
    assert obs.blocklist_index_age.value(tenant="idx-t") >= 0
    assert obs.blocklist_length.value(tenant="idx-t") == 1
