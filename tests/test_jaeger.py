"""Jaeger ingest (thrift UDP agent + collector HTTP) and the Jaeger-UI
query bridge (cmd/tempo-query role).

Fixtures are fabricated with the same thrift codecs' encoders — i.e. the
bytes a real jaeger client library would emit (TBinaryProtocol collector
bodies, TCompactProtocol emitBatch datagrams).
"""

import socket
import time

import pytest

from tempo_tpu import tempopb
from tempo_tpu.api import thriftproto as tp
from tempo_tpu.api.jaeger import (
    JaegerAgentUDP, batch_to_resource_spans, decode_agent_datagram,
    jaeger_thrift_http_to_batches,
)
from tempo_tpu.api.jaeger_query import JaegerQueryBridge, trace_to_jaeger
from tempo_tpu.api.params import _duration_ms
from tempo_tpu.api.http import HTTPApi
from tempo_tpu.modules import App, AppConfig


# ---------------------------------------------------------- thrift codec

STRUCT_CASES = [
    [(1, tp.T_I64, -42), (2, tp.T_I32, 7), (3, tp.T_STRING, "héllo")],
    [(1, tp.T_BOOL, True), (2, tp.T_BOOL, False), (3, tp.T_DOUBLE, 2.5)],
    [(1, tp.T_LIST, (tp.T_I64, [1, -2, 3]))],
    [(5, tp.T_STRUCT, [(1, tp.T_STRING, b"\x00\xff bin")]),
     (200, tp.T_I16, -300)],  # forces full-id encoding in compact
    [(1, tp.T_LIST, (tp.T_STRUCT, [[(1, tp.T_STRING, f"s{i}")]
                                   for i in range(20)]))],  # long list
]


@pytest.mark.parametrize("proto_name", ["binary", "compact"])
@pytest.mark.parametrize("fields", STRUCT_CASES)
def test_thrift_struct_roundtrip(proto_name, fields):
    proto = (tp.BinaryProtocol() if proto_name == "binary"
             else tp.CompactProtocol())
    data = proto.encode_struct(fields)
    got = tp.decode_struct(data, proto_name)

    def norm(ftype, v):
        if ftype == tp.T_STRING:
            return v.encode() if isinstance(v, str) else bytes(v)
        if ftype == tp.T_STRUCT:
            return {fid: norm(ft, vv) for fid, ft, vv in v}
        if ftype == tp.T_LIST:
            et, items = v
            return [norm(et, it) for it in items]
        return v

    for fid, ftype, v in fields:
        assert got[fid] == norm(ftype, v), (proto_name, fid)


@pytest.mark.parametrize("proto_name", ["binary", "compact"])
def test_thrift_message_roundtrip(proto_name):
    proto = (tp.BinaryProtocol() if proto_name == "binary"
             else tp.CompactProtocol())
    msg = proto.encode_message("emitBatch", tp.MSG_ONEWAY, 9,
                               [(1, tp.T_STRING, "payload")])
    name, mtype, seqid, args = tp.decode_message(msg)
    assert (name, mtype, seqid) == ("emitBatch", tp.MSG_ONEWAY, 9)
    assert args[1] == b"payload"


def test_thrift_truncated_and_garbage():
    proto = tp.BinaryProtocol()
    data = proto.encode_struct([(1, tp.T_STRING, "x" * 100)])
    with pytest.raises(tp.ThriftError):
        tp.decode_struct(data[:10], "binary")
    with pytest.raises(tp.ThriftError):
        tp.decode_message(b"\x55\x55\x55")
    with pytest.raises(tp.ThriftError):
        tp.decode_message(b"")


# ----------------------------------------------------- jaeger model


def make_jaeger_batch(proto, service="shop", n_spans=2,
                      trace_low=0x1234, trace_high=0x5678):
    """Encode a jaeger Batch struct with the given protocol's encoder."""
    spans = []
    for i in range(n_spans):
        tags = [
            [(1, tp.T_STRING, "http.status_code"), (2, tp.T_I32, 8),
             (6, tp.T_I64, 200 + i)],
            [(1, tp.T_STRING, "span.kind"), (2, tp.T_I32, 0),
             (3, tp.T_STRING, "server")],
            [(1, tp.T_STRING, "error"), (2, tp.T_I32, 2),
             (5, tp.T_BOOL, i == 1)],
        ]
        logs = [[(1, tp.T_I64, 1_700_000_001_000_000),
                 (2, tp.T_LIST, (tp.T_STRUCT, [
                     [(1, tp.T_STRING, "event"), (3, tp.T_STRING, "retry")],
                     [(1, tp.T_STRING, "attempt"), (6, tp.T_I64, 3)],
                 ]))]]
        refs = [[(1, tp.T_I32, 0), (2, tp.T_I64, trace_low),
                 (3, tp.T_I64, trace_high), (4, tp.T_I64, 99)]]
        spans.append([
            (1, tp.T_I64, trace_low), (2, tp.T_I64, trace_high),
            (3, tp.T_I64, 1000 + i), (4, tp.T_I64, 0),
            (5, tp.T_STRING, f"op-{i}"), (6, tp.T_LIST, (tp.T_STRUCT, refs)),
            (7, tp.T_I32, 1), (8, tp.T_I64, 1_700_000_000_000_000),
            (9, tp.T_I64, 250_000), (10, tp.T_LIST, (tp.T_STRUCT, tags)),
            (11, tp.T_LIST, (tp.T_STRUCT, logs)),
        ])
    batch = [
        (1, tp.T_STRUCT, [(1, tp.T_STRING, service),
                          (2, tp.T_LIST, (tp.T_STRUCT, [
                              [(1, tp.T_STRING, "hostname"),
                               (3, tp.T_STRING, "pod-1")]]))]),
        (2, tp.T_LIST, (tp.T_STRUCT, spans)),
    ]
    return batch


def test_batch_translation_semantics():
    proto = tp.BinaryProtocol()
    body = proto.encode_struct(make_jaeger_batch(proto))
    (rs,) = jaeger_thrift_http_to_batches(body)
    res = {kv.key: kv.value for kv in rs.resource.attributes}
    assert res["service.name"].string_value == "shop"
    assert res["hostname"].string_value == "pod-1"
    spans = rs.scope_spans[0].spans
    assert len(spans) == 2
    s0 = spans[0]
    assert s0.trace_id == (0x5678).to_bytes(8, "big") + (0x1234).to_bytes(8, "big")
    assert s0.span_id == (1000).to_bytes(8, "big")
    assert s0.name == "op-0"
    assert s0.kind == tempopb.Span.SPAN_KIND_SERVER
    assert s0.start_time_unix_nano == 1_700_000_000_000_000_000
    assert s0.end_time_unix_nano - s0.start_time_unix_nano == 250_000_000
    # CHILD_OF ref became the parent
    assert s0.parent_span_id == (99).to_bytes(8, "big")
    attrs = {kv.key: kv.value for kv in s0.attributes}
    assert attrs["http.status_code"].int_value == 200
    assert "span.kind" not in attrs  # consumed into Span.kind
    # error tag → status on span 1 only
    assert spans[1].status.code == 2 and s0.status.code != 2
    # logs → events
    assert s0.events[0].name == "retry"
    ev_attrs = {kv.key: kv.value.int_value for kv in s0.events[0].attributes}
    assert ev_attrs["attempt"] == 3


@pytest.mark.parametrize("proto_name", ["binary", "compact"])
def test_agent_datagram_decode(proto_name):
    proto = (tp.BinaryProtocol() if proto_name == "binary"
             else tp.CompactProtocol())
    dgram = proto.encode_message(
        "emitBatch", tp.MSG_ONEWAY, 1,
        [(1, tp.T_STRUCT, make_jaeger_batch(proto, service="udp-svc"))])
    (rs,) = decode_agent_datagram(dgram)
    assert rs.resource.attributes[0].value.string_value == "udp-svc"
    assert len(rs.scope_spans[0].spans) == 2


def test_agent_rejects_wrong_rpc():
    proto = tp.CompactProtocol()
    dgram = proto.encode_message("somethingElse", tp.MSG_ONEWAY, 1,
                                 [(1, tp.T_I32, 5)])
    with pytest.raises(ValueError):
        decode_agent_datagram(dgram)


# ------------------------------------------------ end-to-end through App


@pytest.fixture
def app(tmp_path):
    a = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    yield a


def test_collector_http_ingest_to_query(app):
    api = HTTPApi(app)
    proto = tp.BinaryProtocol()
    body = proto.encode_struct(make_jaeger_batch(proto))
    code, resp = api.handle("POST", "/api/traces", {},
                            {"X-Scope-OrgID": "t1"}, body)
    assert code == 200, resp
    assert resp["accepted_batches"] == 1
    # readable back through trace-by-id
    tid = ((0x5678).to_bytes(8, "big") + (0x1234).to_bytes(8, "big")).hex()
    code, resp = api.handle("GET", f"/api/traces/{tid}", {},
                            {"X-Scope-OrgID": "t1"})
    assert code == 200

    # malformed body → 400, not 500
    code, _ = api.handle("POST", "/api/traces", {},
                         {"X-Scope-OrgID": "t1"}, b"\x99garbage")
    assert code == 400


def test_udp_agent_end_to_end(app):
    agent = JaegerAgentUDP(app.push, host="127.0.0.1", port=0, tenant="t1")
    try:
        proto = tp.CompactProtocol()
        dgram = proto.encode_message(
            "emitBatch", tp.MSG_ONEWAY, 1,
            [(1, tp.T_STRUCT, make_jaeger_batch(proto))])
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.sendto(dgram, ("127.0.0.1", agent.port))
        sock.sendto(b"junk-datagram", ("127.0.0.1", agent.port))  # ignored
        deadline = time.monotonic() + 5
        while agent.accepted < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert agent.accepted == 1
        deadline = time.monotonic() + 5
        while agent.rejected < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert agent.rejected == 1
        tid = (0x5678).to_bytes(8, "big") + (0x1234).to_bytes(8, "big")
        resp = app.find_trace("t1", tid)
        assert len(resp.trace.batches) == 1
    finally:
        agent.close()


# -------------------------------------------------------- query bridge


def test_trace_to_jaeger_translation():
    proto = tp.BinaryProtocol()
    (rs,) = jaeger_thrift_http_to_batches(
        proto.encode_struct(make_jaeger_batch(proto)))
    t = tempopb.Trace()
    t.batches.append(rs)
    j = trace_to_jaeger(t)
    assert j["traceID"] == ((0x5678).to_bytes(8, "big")
                            + (0x1234).to_bytes(8, "big")).hex()
    assert len(j["spans"]) == 2
    (pid,) = {s["processID"] for s in j["spans"]}
    assert j["processes"][pid]["serviceName"] == "shop"
    s0 = next(s for s in j["spans"] if s["operationName"] == "op-0")
    assert s0["duration"] == 250_000  # µs
    assert {"key": "span.kind", "type": "string",
            "value": "server"} in s0["tags"]
    assert s0["references"][0]["refType"] == "CHILD_OF"
    assert s0["logs"][0]["fields"][0]["value"] == "retry"


def test_jaeger_query_api_end_to_end(app):
    api = HTTPApi(app)
    proto = tp.BinaryProtocol()
    api.handle("POST", "/api/traces", {}, {"X-Scope-OrgID": "t1"},
               proto.encode_struct(make_jaeger_batch(proto)))
    app.flush_tick(force=True)
    app.poll_tick()

    code, resp = api.handle("GET", "/jaeger/api/services", {},
                            {"X-Scope-OrgID": "t1"})
    assert code == 200 and resp["data"] == ["shop"]

    code, resp = api.handle("GET", "/jaeger/api/traces",
                            {"service": "shop", "limit": "5"},
                            {"X-Scope-OrgID": "t1"})
    assert code == 200 and len(resp["data"]) == 1
    assert resp["data"][0]["processes"]["p1"]["serviceName"] == "shop"

    tid = ((0x5678).to_bytes(8, "big") + (0x1234).to_bytes(8, "big")).hex()
    code, resp = api.handle("GET", f"/jaeger/api/traces/{tid}", {},
                            {"X-Scope-OrgID": "t1"})
    assert code == 200 and resp["data"][0]["traceID"] == tid

    code, resp = api.handle("GET", "/jaeger/api/traces/deadbeef00000000", {},
                            {"X-Scope-OrgID": "t1"})
    assert code == 404


@pytest.mark.parametrize("s,ms", [
    ("100ms", 100), ("1.5s", 1500), ("250us", 0), ("2m", 120000),
    ("0.5h", 1800000), ("42", 42),
])
def test_parse_duration(s, ms):
    assert _duration_ms(s) == ms


def test_thrift_nesting_depth_capped():
    """Crafted deep nesting fails with ThriftError (not RecursionError),
    and over HTTP it maps to 400."""
    import struct

    # binary: T_STRUCT header per level, 3 bytes each, depth 2000
    deep_bin = (b"\x0c" + struct.pack(">h", 1)) * 2000
    with pytest.raises(tp.ThriftError):
        tp.decode_struct(deep_bin, "binary")
    # compact: field header (delta 1, type struct) per level
    deep_cpt = b"\x1c" * 2000
    with pytest.raises(tp.ThriftError):
        tp.decode_struct(deep_cpt, "compact")


def test_http_deep_nesting_is_400(tmp_path):
    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    api = HTTPApi(app)
    import struct as _s

    code, _ = api.handle("POST", "/api/traces", {}, {"X-Scope-OrgID": "t"},
                         (b"\x0c" + _s.pack(">h", 1)) * 2000)
    assert code == 400


def test_agent_survives_poison_datagrams(app):
    """RecursionError/overflow-shaped datagrams must not kill the
    receiver thread."""
    cp = tp.CompactProtocol()
    # huge varint that exceeds i64 in a trace-id position
    evil_batch = [(2, tp.T_LIST, (tp.T_STRUCT, [[(1, tp.T_I64, 0)]]))]
    msg = bytearray(cp.encode_message("emitBatch", tp.MSG_ONEWAY, 1,
                                      [(1, tp.T_STRUCT, evil_batch)]))
    agent = JaegerAgentUDP(app.push, host="127.0.0.1", port=0, tenant="t1")
    try:
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        deep = cp.encode_message("emitBatch", tp.MSG_ONEWAY, 1, [])[:-1] \
            + b"\x1c" * 2000
        sock.sendto(deep, ("127.0.0.1", agent.port))
        deadline = time.monotonic() + 5
        while agent.rejected < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert agent.rejected == 1
        # thread still alive: a good datagram is accepted afterwards
        good = cp.encode_message("emitBatch", tp.MSG_ONEWAY, 2,
                                 [(1, tp.T_STRUCT, make_jaeger_batch(cp))])
        sock.sendto(good, ("127.0.0.1", agent.port))
        deadline = time.monotonic() + 5
        while agent.accepted < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert agent.accepted == 1
    finally:
        agent.close()


def test_thrift_negative_name_length_rejected():
    """A crafted negative string length must fail cleanly, not rewind the
    parser position."""
    import struct

    bp = tp.BinaryProtocol()
    evil = struct.pack(">I", bp.VERSION_1 | tp.MSG_ONEWAY) + struct.pack(">i", -8)
    with pytest.raises(tp.ThriftError):
        tp.decode_message(evil + b"\x00" * 16)


def test_operations_filtered_by_service(app):
    api = HTTPApi(app)
    proto = tp.BinaryProtocol()
    api.handle("POST", "/api/traces", {}, {"X-Scope-OrgID": "t1"},
               proto.encode_struct(make_jaeger_batch(proto, service="svc-a",
                                                     trace_low=1)))
    api.handle("POST", "/api/traces", {}, {"X-Scope-OrgID": "t1"},
               proto.encode_struct(make_jaeger_batch(proto, service="svc-b",
                                                     trace_low=2)))
    app.flush_tick(force=True)
    app.poll_tick()
    code, resp = api.handle("GET", "/jaeger/api/services/svc-a/operations",
                            {}, {"X-Scope-OrgID": "t1"})
    assert code == 200
    # svc-b's identically-named root op is NOT attributed to svc-a — the
    # list comes only from svc-a's traces
    code_b, resp_b = api.handle("GET", "/jaeger/api/services/zzz/operations",
                                {}, {"X-Scope-OrgID": "t1"})
    assert resp_b["data"] == []
    assert resp["data"]


def test_jaeger_ui_request_corpus(app):
    """VERDICT r4 #7: a recorded corpus of the requests Jaeger-UI 1.x /
    Grafana's Jaeger datasource actually emit (jaeger-ui src/api/jaeger.js
    request shapes), asserted against the query-service response
    contract: structuredResponse envelope (data/total/limit/offset/
    errors), µs time units, span fields, CHILD_OF references, processes
    table. Documented in docs/jaeger-grafana.md."""
    import json as _json
    import time as _time

    from tempo_tpu.utils.ids import random_trace_id
    from tempo_tpu.utils.test_data import make_trace

    api = HTTPApi(app)
    hdr = {"X-Scope-OrgID": "t1"}
    # seed: two services, parent/child spans
    tids = [random_trace_id() for _ in range(5)]
    for i, tid in enumerate(tids):
        tr = make_trace(tid, seed=i)
        # give every trace a parent/child edge (make_trace emits flat
        # spans): the UI's waterfall depends on CHILD_OF references
        ss0 = tr.batches[0].scope_spans[0]
        child = ss0.spans.add()
        child.CopyFrom(ss0.spans[0])
        child.span_id = random_trace_id()[:8]
        child.parent_span_id = ss0.spans[0].span_id
        child.name = "child-op"
        app.push("t1", list(tr.batches))
    app.flush_tick(force=True)
    app.poll_tick()

    now_us = int(_time.time() * 1e6)
    # the UI computes the window client-side; the fixture traces sit at
    # ~2020 epoch, so this is the "custom time range" form of the query
    start_us = 1_500_000_000 * 1_000_000
    # --- the corpus: (path, query) exactly as the UI issues them ---
    code, services = api.handle("GET", "/jaeger/api/services", {}, hdr)
    assert code == 200
    for env in (services,):
        assert set(env) >= {"data", "total", "limit", "offset", "errors"}
        assert env["errors"] is None and env["total"] == len(env["data"])
    assert services["data"] == sorted(services["data"])
    svc = services["data"][0]

    code, ops = api.handle(
        "GET", f"/jaeger/api/services/{svc}/operations", {}, hdr)
    assert code == 200 and isinstance(ops["data"], list)

    code, deps = api.handle(
        "GET", "/jaeger/api/dependencies",
        {"endTs": str(now_us // 1000), "lookback": "86400000"}, hdr)
    assert code == 200 and deps["data"] == [] and deps["errors"] is None

    # search exactly as the UI's form submit emits it
    code, found = api.handle(
        "GET", "/jaeger/api/traces",
        {"service": svc, "limit": "20", "lookback": "1h",
         "start": str(start_us), "end": str(now_us),
         "maxDuration": "", "minDuration": ""}, hdr)
    assert code == 200 and found["total"] >= 1, found
    jt = found["data"][0]
    assert set(jt) == {"traceID", "spans", "processes"}
    sp = jt["spans"][0]
    assert set(sp) >= {"traceID", "spanID", "operationName", "startTime",
                       "duration", "processID", "references", "tags",
                       "logs"}
    assert sp["startTime"] > 1e15  # µs epoch, not ns or s
    assert sp["processID"] in jt["processes"]
    assert all(p["serviceName"] for p in jt["processes"].values())
    child_refs = [r for t in found["data"] for s in t["spans"]
                  for r in s["references"] if r["refType"] == "CHILD_OF"]
    assert child_refs and all(
        set(r) == {"refType", "traceID", "spanID"} for r in child_refs)

    # tags filter, JSON object form (the UI's tag search box)
    code, tagged = api.handle(
        "GET", "/jaeger/api/traces",
        {"service": svc, "limit": "20",
         "tags": _json.dumps({"http.status_code": "200"})}, hdr)
    assert code == 200
    for t in tagged["data"]:
        # int-typed OTLP attrs surface as int64 jaeger tags; the search
        # itself matches the string form (substring semantics)
        assert any(tag["key"] == "http.status_code"
                   and "200" in str(tag["value"])
                   for s in t["spans"] for tag in s["tags"])

    # trace-by-id (the UI's detail page)
    code, one = api.handle(
        "GET", f"/jaeger/api/traces/{jt['traceID']}", {}, hdr)
    assert code == 200 and one["data"][0]["traceID"] == jt["traceID"]

    # garbage id → client error, not 500 (UI surfaces the message)
    code, err = api.handle("GET", "/jaeger/api/traces/zzzz", {}, hdr)
    assert code in (400, 404)
