"""Multi-process deployment: gossip membership, per-target module wiring
over real gRPC on localhost, and a subprocess e2e through the CLI — the
reference's integration/e2e microservices topology
(config-microservices.tmpl.yaml: distributor / ingester×N / querier /
query-frontend) without Docker."""

import json
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from tempo_tpu import tempopb
from tempo_tpu.db import TempoDBConfig
from tempo_tpu.modules import AppConfig
from tempo_tpu.modules.membership import Memberlist
from tempo_tpu.modules.microservices import ModuleProcess
from tempo_tpu.utils.ids import random_trace_id, trace_id_to_hex
from tempo_tpu.utils.test_data import make_trace


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_for(pred, timeout_s=15.0, interval_s=0.05, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval_s)
    raise TimeoutError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# membership


def _ml(iid, role, join=(), **kw):
    kw.setdefault("gossip_interval_s", 0.1)
    kw.setdefault("suspect_timeout_s", 1.5)
    return Memberlist(iid, role, join=list(join), **kw)


def test_membership_convergence_and_ring():
    a = _ml("ing-a", "ingester", grpc_addr="127.0.0.1:1111")
    b = _ml("ing-b", "ingester", join=[a.gossip_addr],
            grpc_addr="127.0.0.1:2222")
    c = _ml("dist-c", "distributor", join=[a.gossip_addr])
    try:
        wait_for(lambda: len(c.members("ingester")) == 2,
                 what="distributor sees both ingesters")
        wait_for(lambda: len(a.members("distributor")) == 1,
                 what="ingester learns distributor transitively")
        # ring view: both ingesters healthy, addresses travelled
        assert c.ring("ingester").healthy_count() == 2
        addrs = {m.grpc_addr for m in c.members("ingester")}
        assert addrs == {"127.0.0.1:1111", "127.0.0.1:2222"}
        # deterministic tokens: same replica set computed on any node
        tok = 12345
        assert (a.ring("ingester").get(tok, rf=2)
                == c.ring("ingester").get(tok, rf=2))
    finally:
        for m in (a, b, c):
            m.shutdown()


def test_membership_graceful_leave():
    a = _ml("a", "ingester")
    b = _ml("b", "ingester", join=[a.gossip_addr])
    try:
        wait_for(lambda: len(a.members("ingester")) == 2, what="join")
        b.leave()
        wait_for(lambda: len(a.members("ingester")) == 1, what="leave gossip")
        assert a.ring("ingester").healthy_count() == 1
    finally:
        a.shutdown()


def test_membership_suspect_on_silent_death():
    a = _ml("a", "ingester")
    b = _ml("b", "ingester", join=[a.gossip_addr])
    try:
        wait_for(lambda: len(a.members("ingester")) == 2, what="join")
        b.shutdown()  # no leave: simulates a crash
        wait_for(lambda: len(a.members("ingester")) == 1, timeout_s=10,
                 what="suspect timeout")
        # the ring catches up on the next gossip tick
        wait_for(lambda: a.ring("ingester").healthy_count() == 1,
                 timeout_s=5, what="ring expiry")
    finally:
        a.shutdown()


# ---------------------------------------------------------------------------
# in-process microservice topology (real gRPC between modules)


@pytest.fixture
def topology(tmp_path):
    cfg = AppConfig(
        backend={"backend": "local", "local": {"path": str(tmp_path / "blk")}},
        wal_dir=str(tmp_path / "wal"),
        replication_factor=2,
        db=TempoDBConfig(blocklist_poll_s=1),
    )
    procs = []

    def mk(target, iid, join=()):
        # grpc_port=0 = ephemeral: the server binds port 0 and gossip
        # advertises the assigned port — free_port() probing raced other
        # test processes for the same port (observed flaky collision)
        p = ModuleProcess(
            cfg, target, instance_id=iid, grpc_port=0,
            memberlist_cfg={"join": list(join), "gossip_interval_s": 0.1,
                            "suspect_timeout_s": 5.0},
        )
        procs.append(p)
        return p

    yield cfg, mk, procs
    for p in procs:
        try:
            p.shutdown()
        except Exception:
            pass


def test_microservice_topology_end_to_end(topology):
    cfg, mk, procs = topology
    ing1 = mk("ingester", "ing-1")
    seed = [ing1.ml.gossip_addr]
    ing2 = mk("ingester", "ing-2", join=seed)
    dist = mk("distributor", "dist-1", join=seed)
    quer = mk("querier", "quer-1", join=seed)
    front = mk("query-frontend", "front-1", join=seed)

    wait_for(lambda: dist.ready() and front.ready()
             and len(quer.ml.members("ingester")) == 2,
             what="topology convergence")

    # push through the distributor: RF=2 replication over gRPC Pusher
    tids = []
    for i in range(12):
        tid = random_trace_id()
        tids.append(tid)
        dist.push("acme", list(make_trace(tid, seed=100 + i).batches))

    # live read path: frontend → querier → gRPC IngesterQuerier replicas
    resp = front.find_trace(tenant="acme", trace_id=tids[0])
    assert resp.trace.batches, "live trace not found via replica reads"

    # flush both ingesters to the shared backend, poll the readers
    ing1.flush_tick(force=True)
    ing2.flush_tick(force=True)
    quer.db.poll()
    front.db.poll()

    # block read path
    resp = front.find_trace(tenant="acme", trace_id=tids[1])
    assert resp.trace.batches, "trace not found in backend blocks"

    # search across processes (recent + block jobs over gRPC)
    req = tempopb.SearchRequest()
    req.tags["service.name"] = "frontend"
    req.limit = 50
    sresp = front.search("acme", req)
    assert sresp.metrics.inspected_blocks >= 1

    # tag surface through the remote path
    tags = front.queriers[0].search_tags("acme")
    assert "service.name" in tags.tag_names


def test_microservice_ingester_crash_tolerated(topology):
    """RF=2: killing one ingester replica must not lose reads (reference
    write-extension + replica fan-out semantics)."""
    cfg, mk, procs = topology
    ing1 = mk("ingester", "ing-1")
    seed = [ing1.ml.gossip_addr]
    ing2 = mk("ingester", "ing-2", join=seed)
    dist = mk("distributor", "dist-1", join=seed)
    quer = mk("querier", "quer-1", join=seed)

    wait_for(lambda: dist.ready() and len(quer.ml.members("ingester")) == 2,
             what="convergence")

    tid = random_trace_id()
    dist.push("t1", list(make_trace(tid, seed=7).batches))

    # hard-kill one replica (no graceful leave, no flush)
    victim = ing2
    victim.ml.shutdown()
    victim.grpc_server.stop(0)

    resp = quer.querier.find_trace_by_id("t1", tid)
    assert resp.trace.batches, "read lost with one replica down"
    assert resp.metrics.failed_blocks >= 1  # the dead replica was counted


# ---------------------------------------------------------------------------
# subprocess e2e through the CLI (the real deployment shape)


@pytest.mark.slow
def test_cli_microservices_subprocess(tmp_path):
    gossip_seed = f"127.0.0.1:{free_port()}"
    # subprocess e2e keeps free_port(): the CLI's `-grpc-port=0` means
    # "config default" (falsy falls back to 9095 — the frontend pull
    # test below RELIES on that), so the race-free ephemeral bind is
    # only reachable through ModuleProcess directly (the topology
    # fixture above, where the PR 6 flake actually lived)
    ing_grpc = free_port()
    dist_grpc = free_port()
    quer_grpc = free_port()
    dist_http, ing_http, quer_http, front_http = (free_port() for _ in range(4))

    base = f"""
storage:
  backend: local
  local: {{path: {tmp_path}/blocks}}
  wal_dir: {tmp_path}/wal
  poll_tick_s: 1
ingester:
  replication_factor: 1
  flush_tick_s: 1
memberlist:
  join: ["{gossip_seed}"]
  gossip_interval_s: 0.2
"""
    (tmp_path / "ing.yaml").write_text(base.replace(
        'join: ["%s"]' % gossip_seed, 'bind: "%s"' % gossip_seed))
    (tmp_path / "common.yaml").write_text(base)

    import os
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = []

    def spawn(target, cfg, http, grpc, iid):
        p = subprocess.Popen(
            [sys.executable, "-m", "tempo_tpu.cli.main",
             f"-config.file={cfg}", f"-target={target}",
             f"-http-port={http}", f"-grpc-port={grpc}",
             f"-instance-id={iid}"],
            cwd="/root/repo", env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        procs.append(p)
        return p

    try:
        spawn("ingester", tmp_path / "ing.yaml", ing_http, ing_grpc, "ing-0")
        spawn("distributor", tmp_path / "common.yaml", dist_http, dist_grpc,
              "dist-0")
        spawn("querier", tmp_path / "common.yaml", quer_http, quer_grpc,
              "quer-0")
        spawn("query-frontend", tmp_path / "common.yaml", front_http, 0,
              "front-0")

        def ready(port):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/ready", timeout=1) as r:
                    return r.status == 200
            except Exception:
                return False

        wait_for(lambda: all(ready(p) for p in
                             (dist_http, ing_http, quer_http, front_http)),
                 timeout_s=90, interval_s=0.5, what="processes ready")

        # OTLP/HTTP push to the distributor
        tid = random_trace_id()
        payload = make_trace(tid, seed=3).SerializeToString()
        req = urllib.request.Request(
            f"http://127.0.0.1:{dist_http}/v1/traces", data=payload,
            headers={"Content-Type": "application/x-protobuf",
                     "X-Scope-OrgID": "sub"})
        with urllib.request.urlopen(req, timeout=5) as r:
            assert r.status == 200

        # live read via the frontend
        def found():
            try:
                q = urllib.request.Request(
                    f"http://127.0.0.1:{front_http}/api/traces/"
                    f"{trace_id_to_hex(tid)}",
                    headers={"X-Scope-OrgID": "sub"})
                with urllib.request.urlopen(q, timeout=5) as r:
                    return bool(json.loads(r.read()).get("batches"))
            except Exception:
                return False

        wait_for(found, timeout_s=120, interval_s=0.5,
                 what="trace via frontend")

        # flush + backend search
        urllib.request.urlopen(
            f"http://127.0.0.1:{ing_http}/flush", timeout=10)

        def searched():
            try:
                q = urllib.request.Request(
                    f"http://127.0.0.1:{front_http}/api/search?limit=20",
                    headers={"X-Scope-OrgID": "sub"})
                with urllib.request.urlopen(q, timeout=10) as r:
                    doc = json.loads(r.read())
                    return bool(doc.get("traces"))
            except Exception:
                return False

        # generous: four subprocesses cold-compile their JAX kernels
        # while the rest of the suite loads the machine
        wait_for(searched, timeout_s=180, interval_s=0.5,
                 what="backend search via frontend")

        # pull dispatch engages: the frontend binds the default gRPC
        # port and the querier's workers dial in via gossip (early
        # queries may legitimately ride the push fallback while workers
        # are still connecting, so query again once they're in)
        def pull_stats():
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{front_http}/status", timeout=5) as r:
                return json.loads(r.read()).get("pull_dispatch") or {}

        wait_for(lambda: pull_stats().get("workers", 0) >= 1,
                 timeout_s=30, what="pull workers connect")
        q2 = urllib.request.Request(
            f"http://127.0.0.1:{front_http}/api/search?limit=20",
            headers={"X-Scope-OrgID": "sub"})
        with urllib.request.urlopen(q2, timeout=10) as r:
            assert json.loads(r.read()).get("traces")
        assert pull_stats().get("delivered", 0) >= 1, \
            f"post-connect search did not travel over pull: {pull_stats()}"
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_membership_revival_rejoins_ring():
    """A member that goes silent past the suspect timeout and then revives
    must be re-registered in peers' rings, not just re-marked alive."""
    a = _ml("a", "ingester")
    b = _ml("b", "ingester", join=[a.gossip_addr], suspect_timeout_s=0.6)
    a.suspect_timeout_s = 0.6
    try:
        wait_for(lambda: a.ring("ingester").healthy_count() == 2, what="join")
        # silence b: stop its gossip loop but keep its server up so it can
        # still answer a's exchanges with STALE state (paused process)
        b._stop.set()
        wait_for(lambda: a.ring("ingester").healthy_count() == 1,
                 timeout_s=10, what="suspicion")
        # revive: restart b's gossip loop (counter resumes advancing)
        import threading
        b._stop.clear()
        threading.Thread(target=b._loop, daemon=True).start()
        wait_for(lambda: a.ring("ingester").healthy_count() == 2,
                 timeout_s=10, what="revival re-registration")
    finally:
        a.shutdown()
        b.shutdown()


def test_metrics_generator_target_receives_forwarded_spans(topology):
    """Standalone metrics-generator processes get span batches from the
    distributor over the MetricsGenerator/PushSpans gRPC service, routed
    per trace over the generator ring (reference tempo.proto:14-16 +
    distributor metrics_generator forwarder)."""
    cfg, mk, procs = topology
    ing = mk("ingester", "ing-1")
    seed = [ing.ml.gossip_addr]
    gen = ModuleProcess(
        cfg, "metrics-generator", instance_id="gen-1",
        grpc_port=0,  # ephemeral bind, gossip advertises the real port
        memberlist_cfg={"join": seed, "gossip_interval_s": 0.1,
                        "suspect_timeout_s": 5.0},
    )
    procs.append(gen)
    dist = mk("distributor", "dist-1", join=seed)

    wait_for(lambda: dist.ready()
             and len(dist.ml.members("metrics-generator")) == 1,
             what="generator visible to distributor")

    # traces with an explicit client→server parent link so the
    # service-graph processor can PAIR an edge (make_trace spans carry
    # no parent ids — spanmetrics alone would pass trivially)
    for i in range(5):
        tid = random_trace_id()
        batches = []
        client_sid = bytes([i + 1]) * 8
        for svc, kind, sid, parent in (
            ("shop", tempopb.Span.SPAN_KIND_CLIENT, client_sid, b""),
            ("pay", tempopb.Span.SPAN_KIND_SERVER, bytes([99, i]) * 4,
             client_sid),
        ):
            rs = tempopb.ResourceSpans()
            kv = rs.resource.attributes.add()
            kv.key = "service.name"
            kv.value.string_value = svc
            span = rs.scope_spans.add().spans.add()
            span.trace_id = tid
            span.span_id = sid
            if parent:
                span.parent_span_id = parent
            span.name = f"op-{i}"
            span.kind = kind
            span.start_time_unix_nano = 1_600_000_000 * 10**9
            span.end_time_unix_nano = span.start_time_unix_nano + 10**7
            batches.append(rs)
        dist.push("acme", batches)
    dist.distributor.forward_flush()  # drain the async forwarder queue

    def edge_paired():
        exposition = gen.generator.collect("acme")
        return ("traces_service_graph_request_total" in exposition
                and 'client="shop"' in exposition)

    wait_for(edge_paired, timeout_s=15,
             what="service-graph edge paired on the generator target")
    exposition = gen.generator.collect("acme")
    assert "traces_spanmetrics_calls_total" in exposition


def test_push_bytes_v2_method_name_accepted():
    """The reference distributor dials Pusher/PushBytesV2 for
    current-encoding segments; both method names serve the same
    handler."""
    import grpc

    from tempo_tpu.api.grpc_service import make_module_grpc_server

    got = []

    class FakePusher:
        def push_bytes(self, tenant, req):
            got.append((tenant, list(req.ids)))

    # bind port 0 and read the assignment — never probe-then-bind
    server = make_module_grpc_server("127.0.0.1:0", pusher=FakePusher())
    port = server.bound_port
    assert port, "ephemeral gRPC bind failed"
    server.start()
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        for method in ("PushBytes", "PushBytesV2"):
            rpc = ch.unary_unary(
                f"/tempopb.Pusher/{method}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=tempopb.PushResponse.FromString)
            req = tempopb.PushBytesRequest()
            req.ids.append(b"\x01" * 16)
            req.traces.append(b"seg")
            rpc(req, metadata=(("x-scope-orgid", "t"),))
        assert len(got) == 2 and all(t == "t" for t, _ in got)
        ch.close()
    finally:
        server.stop(0)


@pytest.mark.slow
def test_manifest_derived_topology_end_to_end(tmp_path):
    """VERDICT r4 missing #5 (multi-container e2e, sans Docker): the
    TOPOLOGY here is read out of the rendered kube manifests — every
    Deployment/StatefulSet container's `-target=` arg — then booted as
    real CLI subprocesses over gossip, and a trace pushed through the
    manifest-shaped system comes back from search and trace-by-id.
    Replicas collapse to 1 per target to stay CI-fast; the arg/port
    shape is exactly what the containers would run."""
    import os

    import yaml

    kdir = os.path.join(os.path.dirname(__file__), "..", "operations", "kube")
    targets = []
    for name in sorted(os.listdir(kdir)):
        if not name.endswith(".yaml"):
            continue
        with open(os.path.join(kdir, name)) as f:
            for doc in yaml.safe_load_all(f):
                if doc.get("kind") not in ("Deployment", "StatefulSet"):
                    continue
                for c in doc["spec"]["template"]["spec"]["containers"]:
                    tgt = [a.split("=", 1)[1] for a in c.get("args", [])
                           if a.startswith("-target=")]
                    assert tgt, (name, c["name"])
                    targets.append(tgt[0])
    assert {"distributor", "ingester", "querier", "query-frontend",
            "compactor", "metrics-generator"} <= set(targets), targets

    gossip_seed = f"127.0.0.1:{free_port()}"
    base = f"""
storage:
  backend: local
  local: {{path: {tmp_path}/blocks}}
  wal_dir: {tmp_path}/wal
  poll_tick_s: 1
ingester:
  replication_factor: 1
  flush_tick_s: 1
memberlist:
  join: ["{gossip_seed}"]
  gossip_interval_s: 0.2
"""
    (tmp_path / "seed.yaml").write_text(base.replace(
        'join: ["%s"]' % gossip_seed, 'bind: "%s"' % gossip_seed))
    (tmp_path / "common.yaml").write_text(base)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = []
    http_ports = {}
    try:
        for i, tgt in enumerate(dict.fromkeys(targets)):  # 1 per target
            cfg = tmp_path / ("seed.yaml" if i == 0 else "common.yaml")
            http, grpc_p = free_port(), free_port()
            http_ports[tgt] = http
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "tempo_tpu.cli.main",
                 f"-config.file={cfg}", f"-target={tgt}",
                 f"-http-port={http}", f"-grpc-port={grpc_p}",
                 f"-instance-id={tgt}-0"],
                cwd="/root/repo", env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT))

        def ready(port):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/ready", timeout=1) as r:
                    return r.status == 200
            except Exception:
                return False

        for tgt, port in http_ports.items():
            wait_for(lambda p=port: ready(p), timeout_s=60,
                     what=f"{tgt} ready")

        tid = random_trace_id()
        body = make_trace(tid, seed=3).SerializeToString()
        req = urllib.request.Request(
            f"http://127.0.0.1:{http_ports['distributor']}/v1/traces",
            data=body, headers={"X-Scope-OrgID": "m",
                                "Content-Type": "application/x-protobuf"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200

        # flush on the ingester, then read through the query-frontend
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{http_ports['ingester']}/flush",
                headers={"X-Scope-OrgID": "m"}), timeout=10)

        def found():
            try:
                req2 = urllib.request.Request(
                    f"http://127.0.0.1:{http_ports['query-frontend']}"
                    f"/api/traces/{trace_id_to_hex(tid)}",
                    headers={"X-Scope-OrgID": "m"})
                with urllib.request.urlopen(req2, timeout=5) as r:
                    return r.status == 200 and json.loads(r.read())["batches"]
            except Exception:
                return False

        wait_for(found, timeout_s=45, what="trace via manifest topology")
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
