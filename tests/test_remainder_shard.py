"""Remainder-shard mesh layout (ISSUE 16): under
search_structural_remainder_pages the staged page axis pads to the
minimal multiple of the shard count instead of the next pow2 — the last
shard owns the ragged tail, described to the dist kernels by the static
`shard_tail` jit key. Byte-identical to the pow2/replicated layout
(pad entries were already invalid); only the staged footprint and the
compiled layout descriptor change."""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.search import ir
from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
from tempo_tpu.search.multiblock import MultiBlockEngine, compile_multi
from tempo_tpu.search.structural import STRUCTURAL, compile_structural
from test_structural import (  # noqa: F401 — _structural_on is autouse
    _ACCEPTANCE_TRIPLE,
    E_GEO,
    _corpus,
    _expected_ids,
    _mk_req,
    _scan_ids,
    _structural_on,
)

# small pages make ragged page counts cheap to build
G_SMALL = PageGeometry(entries_per_page=8, kv_per_entry=8)


def test_remainder_pad_minimal_multiple_invariants():
    """Page counts 1, n-1, n+1, and primes pad to the minimal multiple
    of the shard count: zero over-pad beyond the ragged tail."""
    STRUCTURAL.remainder_pages = True
    try:
        for n in (2, 3, 4, 5, 8):
            for total in (1, n - 1, n + 1, 2, 3, 5, 7, 11, 13, 17, 23):
                pad = STRUCTURAL.remainder_pad(total, n)
                assert pad % n == 0, (total, n)
                assert pad >= max(total, n)
                # the whole point: never more than one ragged tail
                assert pad - total < n, (total, n, pad)
    finally:
        STRUCTURAL.remainder_pages = False
    # disabled gate: one attribute read, None (pow2 layout kept)
    assert STRUCTURAL.remainder_pad(9, 8) is None


def test_stage_host_minimal_padding_cuts_staged_bytes():
    """A 17-page batch on 8 shards stages 24 pages under the gate, not
    the 32 the pow2 layout takes — measured on the staged arrays."""
    entries = _corpus(11, n=130)  # 130 entries / 8 per page = 17 pages
    blocks = [ColumnarPages.build(entries, G_SMALL)]
    assert sum(b.n_pages for b in blocks) == 17
    eng = MultiBlockEngine(top_k=128)
    eng.n_shards = 8  # host-side layout: no mesh needed
    off = eng.stage_host(blocks)
    assert int(off.page_block.shape[0]) == 32
    STRUCTURAL.remainder_pages = True
    try:
        on = eng.stage_host(blocks)
    finally:
        STRUCTURAL.remainder_pages = False
    assert int(on.page_block.shape[0]) == 24
    assert on.cat_nbytes < off.cat_nbytes
    # the tail pages are pad: no block owns them
    assert (np.asarray(on.page_block)[17:] < 0).all()


def test_span_segment_rebases_on_ragged_layout():
    """Segment-aligned span sharding composes with the minimal-multiple
    page axis: every live span still lands in its trace's shard chunk
    with chunk-local coordinates, including on the short last shard."""
    entries = _corpus(12, n=260)  # 33 pages -> minimal 40 on 8 shards
    blocks = [ColumnarPages.build(entries, G_SMALL)]
    eng = MultiBlockEngine(top_k=128)
    eng.n_shards = 8
    STRUCTURAL.remainder_pages = True
    try:
        host = eng.stage_host(blocks)
    finally:
        STRUCTURAL.remainder_pages = False
    P_pages = int(host.page_block.shape[0])
    assert P_pages == 40
    span_cat = host.span_cat
    assert span_cat is not None
    n_sh = 8
    E = G_SMALL.entries_per_page
    STRUCTURAL.shard_spans = True
    try:
        sh = STRUCTURAL.shard_span_segment(span_cat, n_sh, P_pages, E)
    finally:
        STRUCTURAL.shard_spans = False
    assert sh is not None
    per_shard = sh["span_trace"].shape[0] // n_sh
    pp = P_pages // n_sh
    total_live = 0
    for s in range(n_sh):
        chunk = slice(s * per_shard, (s + 1) * per_shard)
        tr = sh["span_trace"][chunk]
        live = tr >= 0
        total_live += int(live.sum())
        assert (tr[live] < pp * E).all()
        par = sh["span_parent"][chunk][live]
        assert ((par >= -1) & (par < per_shard)).all()
    # nothing dropped by the reshard
    assert total_live == int((span_cat["span_trace"] >= 0).sum())


def _device_ids(entries, geo, mesh, *, remainder: bool):
    """Stage + scan the acceptance triple; returns per-expr result
    sets, counts, and the staged page-axis length."""
    blocks = [ColumnarPages.build(entries, geo)]
    eng = MultiBlockEngine(top_k=512, mesh=mesh)
    STRUCTURAL.remainder_pages = remainder
    try:
        batch = eng.stage(blocks)
    finally:
        STRUCTURAL.remainder_pages = False
    out = []
    all_entries = list(entries)
    for src in _ACCEPTANCE_TRIPLE:
        expr = ir.parse(src)
        req = _mk_req(expr)
        mq = compile_multi(blocks, req, cache_on=batch)
        mq.structural = compile_structural(
            expr, blocks, cache_on=batch,
            staged_dicts=batch.staged_dicts)
        STRUCTURAL.remainder_pages = remainder
        try:
            count, got = _scan_ids(batch, eng, mq, all_entries)
        finally:
            STRUCTURAL.remainder_pages = False
        assert got == _expected_ids(expr, all_entries), (src, remainder)
        out.append((count, got))
    return out, int(batch.device["kv_key"].shape[0])


def test_mesh_remainder_layout_byte_identical():
    """The mesh leg: a non-multiple page count staged remainder-style
    (shard_tail in the jit key) answers identically to the pow2 layout
    and the host reference, with fewer staged pages."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple (forced host) devices")
    from tempo_tpu.parallel import make_mesh

    entries = _corpus(13, n=130)  # 17 pages on 8 shards: 24 vs 32
    mesh = make_mesh()
    got_off, pages_off = _device_ids(entries, G_SMALL, mesh,
                                     remainder=False)
    got_on, pages_on = _device_ids(entries, G_SMALL, mesh,
                                   remainder=True)
    assert got_on == got_off
    assert pages_on < pages_off, (pages_on, pages_off)


def test_mesh_remainder_layout_with_sharded_spans():
    """Remainder layout + segment-aligned span sharding together: the
    short last shard's rebased spans answer identically."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple (forced host) devices")
    from tempo_tpu.parallel import make_mesh

    entries = _corpus(14, n=260)
    mesh = make_mesh()
    STRUCTURAL.shard_spans = True
    try:
        got_off, _ = _device_ids(entries, G_SMALL, mesh,
                                 remainder=False)
        got_on, _ = _device_ids(entries, G_SMALL, mesh, remainder=True)
    finally:
        STRUCTURAL.shard_spans = False
    assert got_on == got_off


def test_dist_engine_remainder_descriptor_byte_identical():
    """DistributedScanEngine already stages minimally; under the gate
    the ragged tail enters the jit key as shard_tail — results stay
    byte-identical to the gate-off compile."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple (forced host) devices")
    from tempo_tpu.parallel import DistributedScanEngine, make_mesh
    from tempo_tpu.search.pipeline import compile_query

    entries = _corpus(15, n=130)
    pages = ColumnarPages.build(entries, G_SMALL)
    eng = DistributedScanEngine(make_mesh(), top_k=512)
    sp = eng.stage(pages)
    for remainder in (False, True):
        STRUCTURAL.remainder_pages = remainder
        try:
            for src in _ACCEPTANCE_TRIPLE:
                expr = ir.parse(src)
                req = _mk_req(expr)
                cq = compile_query(pages.key_dict, pages.val_dict, req,
                                   cache_on=pages)
                cq.structural = compile_structural(expr, [pages],
                                                   cache_on=pages)
                count, _ins, scores, idx = eng.scan_staged(sp, cq)
                want = _expected_ids(expr, entries)
                E = G_SMALL.entries_per_page
                got = set()
                for s, i in zip(scores.tolist(), idx.tolist()):
                    if s < 0:
                        break
                    p, e = divmod(i, E)
                    if p < pages.n_pages:
                        got.add(bytes(pages.trace_ids[p, e]))
                assert got == want and count == len(want), \
                    (src, remainder)
        finally:
            STRUCTURAL.remainder_pages = False
