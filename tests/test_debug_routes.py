"""/debug/* route contract (the CI half of the DEBUG_ROUTES registry in
api/http.py): EVERY registered debug route must

  1. answer 200 with a JSON-serializable body when
     `server.debug_endpoints` is on,
  2. answer 404 when it is off (the gate is one shared check — a route
     that bypasses it would leak stacks/internals on the serving port),

against a real single-binary App. Before this test each endpoint was
hand-verified (or not at all) — a new route added to the registry is
now covered automatically."""

import json

import pytest

from tempo_tpu.api.http import DEBUG_ROUTES, HTTPApi
from tempo_tpu.modules import App, AppConfig


@pytest.fixture(scope="module")
def app(tmp_path_factory):
    from tempo_tpu.utils.test_data import make_trace
    from tempo_tpu.utils.ids import random_trace_id

    a = App(AppConfig(
        wal_dir=str(tmp_path_factory.mktemp("wal"))))
    # a little real state so the pages render content, not empty shells
    tr = make_trace(random_trace_id(), seed=1)
    a.push("dbg-t", list(tr.batches))
    a.flush_tick(force=True)
    a.poll_tick()
    return a


def test_registry_covers_the_known_routes():
    # additions are welcome; REMOVALS of a documented route are not
    assert {"/debug/threads", "/debug/scan", "/debug/profile",
            "/debug/planner", "/debug/querystats",
            "/debug/ingest", "/debug/flightrecorder"} <= set(DEBUG_ROUTES)


def test_every_route_documented_in_observability_md():
    """The debug-routes drift catalog: every DEBUG_ROUTES entry must
    appear (backticked) in docs/observability.md's route index."""
    from tempo_tpu.analysis.drift import catalog_findings

    findings = catalog_findings("debug-routes")
    assert findings == [], "\n".join(f.render() for f in findings)


@pytest.mark.parametrize("path", sorted(DEBUG_ROUTES))
def test_every_debug_route_returns_valid_json_when_enabled(app, path):
    api = HTTPApi(app, debug_endpoints=True)
    code, body = api.handle("GET", path, {}, {})
    assert code == 200, f"{path} -> {code}: {body}"
    # the wire layer serializes dict/list bodies via json.dumps and
    # str bodies as text — either way the payload must be expressible
    # as valid JSON (the contract ISSUE 8 asks for)
    json.loads(json.dumps(body))


@pytest.mark.parametrize("path", sorted(DEBUG_ROUTES))
def test_every_debug_route_is_gated(app, path):
    api = HTTPApi(app, debug_endpoints=False)
    code, body = api.handle("GET", path, {}, {})
    assert code == 404
    assert "debug endpoints disabled" in body["error"]


def test_unknown_debug_path_is_404_both_ways(app):
    for enabled in (True, False):
        api = HTTPApi(app, debug_endpoints=enabled)
        code, _ = api.handle("GET", "/debug/nope", {}, {})
        assert code == 404


def test_recent_param_is_respected_where_supported(app):
    api = HTTPApi(app, debug_endpoints=True)
    for path in ("/debug/profile", "/debug/planner", "/debug/querystats"):
        code, body = api.handle("GET", path, {"recent": "0"}, {})
        assert code == 200
        assert body.get("recent") == []
    # garbage falls back to the default instead of 500ing a debug page
    code, _ = api.handle("GET", "/debug/profile", {"recent": "x"}, {})
    assert code == 200
