import random

from tempo_tpu import tempopb
from tempo_tpu.model import (
    codec_for,
    segment_codec_for,
    combine_trace_protos,
    matches,
    trace_search_metadata,
)
from tempo_tpu.utils import token_for, trace_id_to_hex, hex_to_trace_id, random_trace_id
from tempo_tpu.utils.hashing import fnv1a_32, fnv1a_32_batch
from tempo_tpu.utils.test_data import make_trace

import numpy as np


def test_fnv1a_known_vectors():
    # standard fnv1a-32 test vectors
    assert fnv1a_32(b"") == 0x811C9DC5
    assert fnv1a_32(b"a") == 0xE40C292C
    assert fnv1a_32(b"foobar") == 0xBF9CF968


def test_fnv1a_batch_matches_scalar():
    ids = np.frombuffer(b"".join(bytes([i] * 16) for i in range(32)), dtype=np.uint8)
    ids = ids.reshape(32, 16)
    batch = fnv1a_32_batch(ids)
    for i in range(32):
        assert batch[i] == fnv1a_32(bytes(ids[i]))


def test_token_for_deterministic():
    tid = b"\x01" * 16
    assert token_for("t1", tid) == token_for("t1", tid)
    assert token_for("t1", tid) != token_for("t2", tid)


def test_trace_id_hex_roundtrip():
    tid = random_trace_id()
    assert hex_to_trace_id(trace_id_to_hex(tid)) == tid
    # short ids are left-padded
    assert hex_to_trace_id("abcd") == b"\x00" * 14 + b"\xab\xcd"


def test_codec_v2_roundtrip_and_fastrange():
    tid = random_trace_id()
    tr = make_trace(tid, seed=7)
    c = codec_for("v2")
    obj = c.marshal(tr, start=100, end=200)
    assert c.fast_range(obj) == (100, 200)
    got = c.prepare_for_read(obj)
    assert got == tr


def test_codec_v1_roundtrip():
    tid = random_trace_id()
    tr = make_trace(tid, seed=3)
    c = codec_for("v1")
    obj = c.marshal(tr)
    assert c.fast_range(obj) is None
    assert c.prepare_for_read(obj) == tr


def test_segment_codec_combines_ranges():
    tid = random_trace_id()
    sc = segment_codec_for("v2")
    t1, t2 = make_trace(tid, seed=1, batches=1), make_trace(tid, seed=2, batches=1)
    s1 = sc.prepare_for_write(t1, 10, 20)
    s2 = sc.prepare_for_write(t2, 5, 15)
    obj = sc.to_object([s1, s2])
    assert codec_for("v2").fast_range(obj) == (5, 20)
    got = codec_for("v2").prepare_for_read(obj)
    assert len(got.batches) == 2


def test_combine_dedupes_spans():
    tid = random_trace_id()
    tr = make_trace(tid, seed=5)
    merged = combine_trace_protos([tr, tr])
    n_spans = sum(len(ss.spans) for b in merged.batches for ss in b.scope_spans)
    orig = sum(len(ss.spans) for b in tr.batches for ss in b.scope_spans)
    assert n_spans == orig


def test_combine_merges_distinct():
    tid = random_trace_id()
    t1 = make_trace(tid, seed=1, batches=1, spans_per_batch=1)
    t2 = make_trace(tid, seed=2, batches=1, spans_per_batch=1)
    merged = combine_trace_protos([t1, t2])
    n_spans = sum(len(ss.spans) for b in merged.batches for ss in b.scope_spans)
    assert n_spans == 2


def _mk_req(**kw):
    req = tempopb.SearchRequest()
    for k, v in kw.pop("tags", {}).items():
        req.tags[k] = v
    for k, v in kw.items():
        setattr(req, k, v)
    return req


def test_matches_tag_substring():
    tid = random_trace_id()
    tr = tempopb.Trace()
    b = tr.batches.add()
    kv = b.resource.attributes.add()
    kv.key = "service.name"
    kv.value.string_value = "checkout-service"
    s = b.scope_spans.add().spans.add()
    s.start_time_unix_nano = 1_000_000_000
    s.end_time_unix_nano = 3_000_000_000

    assert matches(tr, _mk_req(tags={"service.name": "checkout"}))
    assert matches(tr, _mk_req(tags={"service.name": "checkout-service"}))
    assert not matches(tr, _mk_req(tags={"service.name": "cart"}))
    assert not matches(tr, _mk_req(tags={"other.key": "checkout"}))


def test_matches_duration_and_window():
    tid = random_trace_id()
    tr = tempopb.Trace()
    s = tr.batches.add().scope_spans.add().spans.add()
    s.start_time_unix_nano = 10 * 10**9
    s.end_time_unix_nano = 12 * 10**9  # 2000ms

    assert matches(tr, _mk_req(min_duration_ms=1000))
    assert not matches(tr, _mk_req(min_duration_ms=3000))
    assert not matches(tr, _mk_req(max_duration_ms=1000))
    assert matches(tr, _mk_req(start=5, end=20))
    assert not matches(tr, _mk_req(start=13, end=20))
    assert not matches(tr, _mk_req(start=1, end=9))


def test_matches_int_attr():
    tr = tempopb.Trace()
    b = tr.batches.add()
    s = b.scope_spans.add().spans.add()
    kv = s.attributes.add()
    kv.key = "http.status_code"
    kv.value.int_value = 500
    assert matches(tr, _mk_req(tags={"http.status_code": "500"}))
    assert not matches(tr, _mk_req(tags={"http.status_code": "200"}))


def test_search_metadata_root():
    tid = random_trace_id()
    tr = tempopb.Trace()
    b = tr.batches.add()
    kv = b.resource.attributes.add()
    kv.key = "service.name"
    kv.value.string_value = "frontend"
    ss = b.scope_spans.add()
    root = ss.spans.add()
    root.name = "GET /"
    root.span_id = b"\x01" * 8
    root.start_time_unix_nano = 10**9
    root.end_time_unix_nano = 2 * 10**9
    child = ss.spans.add()
    child.name = "db.query"
    child.span_id = b"\x02" * 8
    child.parent_span_id = root.span_id
    child.start_time_unix_nano = int(1.1e9)
    child.end_time_unix_nano = int(1.5e9)

    m = trace_search_metadata(tid, tr)
    assert m.root_trace_name == "GET /"
    assert m.root_service_name == "frontend"
    assert m.duration_ms == 1000
    assert m.trace_id == tid.hex()


def test_make_trace_deterministic():
    tid = random_trace_id()
    assert make_trace(tid, seed=42) == make_trace(tid, seed=42)
