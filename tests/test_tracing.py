"""Self-tracing subsystem (observability/tracing).

Mirrors the reference's tracer-init + spanlogger role (cmd/tempo/main.go
installOpenTelemetryTracer, pkg/util/spanlogger): span lifecycle and
parenting, sampling, W3C propagation, batch export, and the
"tempo traces tempo" self-ingest loop end-to-end through a real App.
"""

import logging
import threading
import time

import pytest

from tempo_tpu import tempopb
from tempo_tpu.modules import App, AppConfig
from tempo_tpu.observability import tracing
from tempo_tpu.observability.tracing import (
    BatchProcessor, CollectExporter, SelfExporter, Span, SpanLogger,
    SyncProcessor, Tracer, extract_traceparent, inject_traceparent,
    spans_to_resource_spans,
)


@pytest.fixture(autouse=True)
def _clean_tracer():
    yield
    tracing.set_tracer(None)


def _tracer(ratio=1.0):
    exp = CollectExporter()
    return Tracer(SyncProcessor(exp), sample_ratio=ratio), exp


def test_span_lifecycle_and_attributes():
    tr, exp = _tracer()
    with tr.start_span("op", tenant="t1") as span:
        span.set_attribute("k", 42)
        span.add_event("milestone", n=1)
    (s,) = exp.spans
    assert s.name == "op"
    assert s.attributes == {"tenant": "t1", "k": 42}
    assert s.end_ns >= s.start_ns
    assert s.events[0][1] == "milestone"
    assert len(s.context.trace_id) == 16 and len(s.context.span_id) == 8


def test_span_parenting_nested():
    tr, exp = _tracer()
    with tr.start_span("parent") as p:
        with tr.start_span("child") as c:
            assert c.context.trace_id == p.context.trace_id
            assert c.parent_span_id == p.context.span_id
    # both exported, same trace
    assert {s.name for s in exp.spans} == {"parent", "child"}


def test_parenting_across_threads():
    """contextvars copy into threads started with a copied context."""
    import contextvars

    tr, exp = _tracer()
    child_ids = []
    with tr.start_span("parent") as p:
        ctx = contextvars.copy_context()

        def work():
            with tr.start_span("worker") as w:
                child_ids.append((w.context.trace_id, w.parent_span_id))

        t = threading.Thread(target=ctx.run, args=(work,))
        t.start()
        t.join()
    assert child_ids == [(p.context.trace_id, p.context.span_id)]


def test_sampling_zero_ratio_is_noop():
    tr, exp = _tracer(ratio=0.0)
    with tr.start_span("never") as s:
        assert not s.recording
        # all mutators are free no-ops
        s.set_attribute("a", 1).add_event("e").set_status(2)
    assert exp.spans == []


def test_child_inherits_sampling_decision():
    tr, exp = _tracer(ratio=0.0)
    with tr.start_span("root") as r:
        with tr.start_span("child") as c:
            assert not c.recording
            # same trace: the negative decision propagated, the child did
            # not re-roll into a fresh root trace
            assert c.context.trace_id == r.context.trace_id
    assert exp.spans == []


def test_remote_unsampled_parent_suppresses_whole_stack():
    """traceparent flags 00 → no span anywhere below, and outgoing
    injection forwards the negative decision."""
    tr, exp = _tracer(ratio=1.0)
    ctx = extract_traceparent(
        {"traceparent": "00-" + "ab" * 16 + "-" + "cd" * 8 + "-00"})
    assert ctx is not None and not ctx.sampled
    with tr.start_span("server", parent=ctx) as s:
        assert not s.recording
        with tr.start_span("inner") as i:
            assert not i.recording
            hdrs = inject_traceparent({})
    assert exp.spans == []
    assert hdrs["traceparent"].startswith("00-" + "ab" * 16)
    assert hdrs["traceparent"].endswith("-00")


def test_grpc_client_metadata_carries_traceparent():
    from tempo_tpu.api.grpc_service import _Base

    tr, _ = _tracer()
    tracing.set_tracer(tr)
    client = _Base.__new__(_Base)
    client.tenant = None
    with tr.start_span("client-call") as s:
        md = dict(client._md("t1"))
    assert md["x-scope-orgid"] == "t1"
    assert md["traceparent"].split("-")[1] == s.context.trace_id.hex()


def test_exception_recorded_and_status_error():
    tr, exp = _tracer()
    with pytest.raises(ValueError):
        with tr.start_span("boom"):
            raise ValueError("bad")
    (s,) = exp.spans
    assert s.status_code == tracing.STATUS_ERROR
    assert s.events[0][1] == "exception"
    assert s.events[0][2]["exception.type"] == "ValueError"


def test_module_level_noop_without_tracer():
    tracing.set_tracer(None)
    with tracing.start_span("free") as s:
        assert s is tracing.NOOP_SPAN


def test_traceparent_roundtrip():
    tr, _ = _tracer()
    hdrs = {}
    with tr.start_span("client"):
        inject_traceparent(hdrs)
    ctx = extract_traceparent(hdrs)
    assert ctx is not None and ctx.sampled
    # remote parent continues the trace
    with tr.start_span("server", parent=ctx) as s:
        assert s.context.trace_id == ctx.trace_id
        assert s.parent_span_id == ctx.span_id


@pytest.mark.parametrize("header", [
    "", "garbage", "00-short-aaaa-01",
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # all-zero trace id
    "00-" + "1" * 32 + "-" + "0" * 16 + "-01",  # all-zero parent id
    "00-" + "1" * 32 + "-" + "1" * 16 + "-1",   # short flags
    "00-zz" + "0" * 30 + "-" + "1" * 16 + "-01",  # non-hex
])
def test_traceparent_rejects_malformed(header):
    assert extract_traceparent({"traceparent": header} if header else {}) is None


def test_batch_processor_flushes_and_bounds():
    exp = CollectExporter()
    proc = BatchProcessor(exp, max_batch=4, max_queue=8, interval_s=0.05)
    tr = Tracer(proc)
    for i in range(6):
        tr.start_span(f"s{i}").end()
    deadline = time.monotonic() + 5
    while len(exp.spans) < 6 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert len(exp.spans) == 6
    proc.shutdown()


def test_spans_to_resource_spans_wire():
    tr, exp = _tracer()
    with tr.start_span("a", tenant="x") as s:
        s.add_event("ev", detail="d")
    rs = spans_to_resource_spans(exp.spans, "svc", "inst-1")
    res_attrs = {kv.key: kv.value.string_value
                 for kv in rs.resource.attributes}
    assert res_attrs["service.name"] == "svc"
    (span,) = rs.scope_spans[0].spans
    assert span.name == "a"
    assert span.end_time_unix_nano >= span.start_time_unix_nano
    attrs = {kv.key: kv.value.string_value for kv in span.attributes}
    assert attrs["tenant"] == "x"
    assert span.events[0].name == "ev"
    # the batch is a valid tempopb.Trace member (self-ingest wire format)
    t = tempopb.Trace()
    t.batches.append(rs)
    assert tempopb.Trace.FromString(t.SerializeToString())


def test_self_export_suppression_no_recursion():
    """Exporting spans through a push path that itself creates spans must
    not recurse: the exporter thread is suppressed."""
    depth = []

    class TracingPush:
        def __call__(self, tenant, batches):
            with tracing.start_span("push-internal") as s:
                depth.append(s.recording)

    exp = SelfExporter(TracingPush())
    tr = Tracer(SyncProcessor(exp))
    tracing.set_tracer(tr)
    tr.start_span("outer").end()
    assert depth == [False]  # inner span was noop — no recursion


def test_spanlogger_couples_logs_to_span(caplog):
    tr, exp = _tracer()
    tracing.set_tracer(tr)
    with caplog.at_level(logging.INFO, logger="tempo_tpu"):
        with SpanLogger("frontend.Search", tenant="t1") as sl:
            sl.log("inspected", level=logging.INFO, blocks=3)
    (s,) = exp.spans
    assert s.attributes["tenant"] == "t1"
    assert s.events[0][1] == "inspected"
    assert s.events[0][2] == {"blocks": 3}
    assert any("inspected" in r.message for r in caplog.records)


def test_app_self_tracing_end_to_end(tmp_path):
    """Query spans land back in the framework and are searchable — the
    reference's "tempo traces tempo" deployment, in-process."""
    app = App(AppConfig(
        wal_dir=str(tmp_path / "wal"),
        self_tracing={"enabled": True, "exporter": "self", "tenant": "self",
                      "flush_interval_s": 0.05},
    ))
    try:
        assert app.tracer is not None
        # generate traced work: a search against an empty store
        req = tempopb.SearchRequest()
        req.tags["service.name"] = "nope"
        app.search("t1", req)
        app.tracer.processor.force_flush()

        # exported spans entered the distributor as tenant "self" and are
        # queryable through the normal read path (live-trace search)
        sreq = tempopb.SearchRequest()
        sreq.tags["service.name"] = "tempo-tpu"
        deadline = time.monotonic() + 5
        resp = None
        while time.monotonic() < deadline:
            resp = app.frontend.search("self", sreq)
            if len(resp.traces):
                break
            time.sleep(0.05)
        assert resp is not None and len(resp.traces) >= 1
    finally:
        app.shutdown()


def test_frontend_and_tempodb_spans_emitted(tmp_path):
    """The instrumented layers emit the reference's span names."""
    exp = CollectExporter()
    tracing.set_tracer(Tracer(SyncProcessor(exp)))
    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    from tempo_tpu.utils.ids import random_trace_id
    from tempo_tpu.utils.test_data import make_trace

    tid = random_trace_id()
    app.push("t1", list(make_trace(tid, seed=1).batches))
    app.flush_tick(force=True)
    app.poll_tick()
    app.frontend.find_trace_by_id("t1", tid)
    req = tempopb.SearchRequest()
    req.tags["service.name"] = "svc"
    app.frontend.search("t1", req)
    names = {s.name for s in exp.spans}
    assert "frontend.TraceByID" in names
    assert "frontend.Search" in names
    assert "tempodb.Find" in names
    assert "ingester.CompleteBlock" in names
    # frontend span parents the tempodb span (same trace)
    by_name = {}
    for s in exp.spans:
        by_name.setdefault(s.name, s)
    assert (by_name["tempodb.Find"].context.trace_id
            == by_name["frontend.TraceByID"].context.trace_id)
