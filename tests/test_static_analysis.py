"""Tier-1 static-analysis suite (tempo_tpu/analysis/ + scripts/check.py).

Two directions per checker:
  - the REAL package is clean: zero un-allowlisted findings, zero stale
    allowlist entries (the suite-at-zero-by-construction contract);
  - the known-bad fixture package (tests/fixtures/analysis_bad/) is
    flagged: the PR 1 rendezvous-deadlock lock cycle by the lock-order
    analyzer, the gate-violating noop path by the contract checker, the
    tracer .item() in a jit body by the purity lint — and the clean
    twins in the same files stay unflagged (precision, not just recall).

Plus the CLI/CI surface (exit codes, --json), allowlist semantics
(stale entries fail, justifications are mandatory, fingerprints survive
line drift), the <10s single-parse-pass runtime contract, and
mypy --strict over the annotated core subset (skipped where mypy is not
installed — the container bakes no new deps).
"""

import json
import os
import subprocess
import sys
import time

import pytest

from tempo_tpu.analysis import (
    default_checkers,
    load_allowlist,
    run_suite,
)
from tempo_tpu.analysis.allowlist import (
    AllowlistError,
    _parse_subset,
    default_path,
)
from tempo_tpu.analysis.core import Finding, Package
from tempo_tpu.analysis.contracts import GatedFunction, NoopContractChecker
from tempo_tpu.analysis.jit_purity import JitPurityChecker
from tempo_tpu.analysis.locks import LockOrderChecker

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_PKG = os.path.join(_ROOT, "tempo_tpu")
_FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


@pytest.fixture(scope="module")
def real_pkg():
    return Package.load(_PKG)


@pytest.fixture(scope="module")
def bad_pkg():
    return Package.load(os.path.join(_FIXTURES, "analysis_bad"),
                        rel_base=_FIXTURES)


# ------------------------------------------------------------ the suite


def test_suite_clean_over_package(real_pkg):
    """THE tier-1 gate: all four checkers over tempo_tpu/, zero
    un-allowlisted findings, zero stale allowlist entries, single parse
    pass, under 10 seconds."""
    t0 = time.perf_counter()
    report = run_suite(real_pkg, default_checkers(),
                       load_allowlist(default_path()))
    elapsed = time.perf_counter() - t0
    assert not report.findings, (
        "static-analysis findings (fix them, or add a justified "
        "allowlist entry):\n" + report.render())
    assert not report.stale, (
        "stale allowlist entries (the defect they justified is gone — "
        "delete them):\n" + report.render())
    assert report.exit_code == 0
    assert elapsed < 10.0, f"suite took {elapsed:.1f}s (contract: <10s)"


def test_allowlist_entries_all_carry_justifications():
    allowlist = load_allowlist(default_path())
    for e in allowlist.entries:
        assert e.justification.strip(), e.fingerprint
        assert len(e.justification) > 20, (
            f"{e.fingerprint}: a justification must say WHY, not just "
            "wave")


# ------------------------------------------- lock-order (PR 1 fixture)


def test_lock_order_flags_rendezvous_deadlock_cycle(bad_pkg):
    findings = LockOrderChecker().check(bad_pkg)
    cycles = [f for f in findings if f.key.startswith("cycle:")]
    # ONE strongly connected component: the direct A<->B cycle and the
    # B<->enqueue cycle share queue_lock_b, so Tarjan reports them as
    # one deadlock-prone lock cluster
    assert len(cycles) == 1, [f.message for f in findings]
    msg = cycles[0].message
    assert "queue_lock_a" in msg and "queue_lock_b" in msg
    # enqueue_lock is only reachable through the context-manager helper
    # (the locked_collective shape): its presence in the SCC proves
    # with-item helper acquisitions propagate into caller summaries
    assert "enqueue_lock" in msg
    assert "deadlock" in msg


def test_lock_order_flags_blocking_under_lock(bad_pkg):
    findings = LockOrderChecker().check(bad_pkg)
    blocking = sorted((f for f in findings
                       if f.key.startswith("blocking:")),
                      key=lambda f: f.line)
    msgs = [f.message for f in blocking]
    assert len(blocking) == 2, msgs
    assert "wait_under_lock" in msgs[0] and ".result" in msgs[0]
    # result(None) is explicitly unbounded — an argument being present
    # must not pass for a bounding timeout
    assert "wait_none_under_lock" in msgs[1]
    # acquire(blocking=False) returns immediately: the clean twin
    assert not [f for f in findings
                if "clean_try_acquire" in f.message]


def test_lock_order_flags_reacquire_through_call(bad_pkg):
    findings = LockOrderChecker().check(bad_pkg)
    re_acq = [f for f in findings if f.key.startswith("reacquire:")]
    assert len(re_acq) == 1, [f.message for f in findings]
    assert "self-deadlock" in re_acq[0].message


def test_lock_order_clean_twin_not_flagged(bad_pkg):
    """clean_dispatch: consistent order + bounded result() — silent."""
    findings = LockOrderChecker().check(bad_pkg)
    assert not [f for f in findings if "clean_dispatch" in f.message]


def test_lock_order_clean_on_real_package(real_pkg):
    """The PR-level contract: the real lock graph is cycle-free and no
    blocking call survives under a lock (the fence/_FusedOut fixes)."""
    assert LockOrderChecker().check(real_pkg) == []


# ------------------------------------------------- noop-contract


_FIXTURE_GATES = (
    GatedFunction("analysis_bad.noop_gate", "Telemetry.record_thing",
                  ("enabled",), "fixture_knob"),
    GatedFunction("analysis_bad.noop_gate", "Telemetry.record_clean",
                  ("enabled",), "fixture_knob"),
)


def test_contract_flags_pre_gate_work_and_unguarded_calls(bad_pkg):
    findings = NoopContractChecker(gated=_FIXTURE_GATES).check(bad_pkg)
    keys = sorted(f.key.split(":")[0] for f in findings)
    assert keys == ["pre-gate", "pre-gate"] + ["unguarded"] * 7, \
        [f.message for f in findings]
    msgs = " | ".join(f.message for f in findings)
    assert "metric write" in msgs and "clock read" in msgs
    assert "FAULTS.hit()" in msgs and "TELEMETRY.record_age()" in msgs
    # the hedge-timer rule: an estimator touch without the armed gate
    # is flagged; the guarded twin stays silent
    assert "hedge_unguarded" in msgs and "HEDGE.observe()" in msgs
    assert "hedge_guarded" not in msgs
    # the analytics rule: staging without the enabled gate is flagged;
    # the guarded twin stays silent
    assert "analytics_unguarded" in msgs
    assert "ANALYTICS.stage_for_batch()" in msgs
    assert "analytics_guarded" not in msgs
    # polarity: `if FAULTS.active: return` exits on the ARMED path —
    # it must NOT count as a guard for what follows; and the else
    # branch of a gate test is the gate-OFF path
    assert "hit_inverted_gate" in msgs and "hit_in_else" in msgs
    # a record call used as a context manager is still a record call
    assert "record_with_item" in msgs and "record_span" in msgs
    # the good twins stay silent
    assert "record_clean" not in msgs and "hit_guarded" not in msgs


def test_contract_registry_drift_is_a_finding(bad_pkg):
    gone = (GatedFunction("analysis_bad.noop_gate", "Telemetry.deleted",
                          ("enabled",), "fixture_knob"),)
    findings = NoopContractChecker(gated=gone, guarded=()).check(bad_pkg)
    assert any(f.key.startswith("gate-missing:") for f in findings)


# ------------------------------------------------- jit-purity


def test_jit_purity_flags_tracer_leaks(bad_pkg):
    findings = JitPurityChecker().check(bad_pkg)
    kinds = sorted(f.key.split(":")[0] for f in findings
                   if "leaky_kernel" in f.key)
    assert kinds == sorted(["clock", "tracer-branch", "item",
                            "np-host", "scalar-sync"]), \
        [f.message for f in findings]


def test_jit_purity_flags_missing_static_decl(bad_pkg):
    findings = JitPurityChecker().check(bad_pkg)
    decl = [f for f in findings if f.key.startswith("static-decl:")]
    assert len(decl) == 1 and "top_k" in decl[0].message


def test_jit_purity_clean_twin_not_flagged(bad_pkg):
    findings = JitPurityChecker().check(bad_pkg)
    assert not [f for f in findings if "clean_kernel" in f.message], \
        [f.message for f in findings]


def test_jit_purity_flags_tainted_width_descriptor(bad_pkg):
    findings = JitPurityChecker().check(bad_pkg)
    taint = [f for f in findings if f.key.startswith("descriptor-taint:")
             and "descriptor_taint_kernel" in f.key]
    assert taint and "'w'" in taint[0].message, \
        [f.message for f in findings]
    assert not [f for f in findings
                if "descriptor_clean_kernel" in f.key], \
        [f.message for f in findings]


def test_jit_purity_flags_tainted_plan_descriptor(bad_pkg):
    """The structural engine's static plan descriptors are covered by
    the same rule as the packed-residency widths: tracer data reaching
    a plan-dispatching helper is flagged; the static twin stays
    silent."""
    findings = JitPurityChecker().check(bad_pkg)
    taint = [f for f in findings if f.key.startswith("descriptor-taint:")
             and "plan_taint_kernel" in f.key]
    assert taint and "'plan'" in taint[0].message, \
        [f.message for f in findings]
    assert not [f for f in findings if "plan_clean_kernel" in f.key], \
        [f.message for f in findings]


def test_jit_purity_flags_tainted_span_layout_descriptor(bad_pkg):
    """The span-sharding layout flag is a descriptor like widths/plan:
    tracer data reaching a layout-dispatching helper is flagged; the
    static twin stays silent."""
    findings = JitPurityChecker().check(bad_pkg)
    taint = [f for f in findings if f.key.startswith("descriptor-taint:")
             and "span_layout_taint_kernel" in f.key]
    assert taint and "'span_sharded'" in taint[0].message, \
        [f.message for f in findings]
    assert not [f for f in findings
                if "span_layout_clean_kernel" in f.key], \
        [f.message for f in findings]


def test_jit_purity_flags_tainted_bucket_descriptor(bad_pkg):
    """The shape-bucket descriptor (bucketed cross-plan stacking) is a
    descriptor like widths/plan/span_sharded: tracer data reaching a
    bucket-dispatching helper is flagged; the static twin stays
    silent."""
    findings = JitPurityChecker().check(bad_pkg)
    taint = [f for f in findings if f.key.startswith("descriptor-taint:")
             and "bucket_taint_kernel" in f.key]
    assert taint and "'bucket'" in taint[0].message, \
        [f.message for f in findings]
    assert not [f for f in findings
                if "bucket_clean_kernel" in f.key], \
        [f.message for f in findings]


def test_jit_purity_flags_tainted_tier_descriptor(bad_pkg):
    """The hot-tier page-capacity descriptor is a descriptor like
    widths/plan/span_sharded: tracer data reaching a tier-dispatching
    helper is flagged; the static twin stays silent."""
    findings = JitPurityChecker().check(bad_pkg)
    taint = [f for f in findings if f.key.startswith("descriptor-taint:")
             and "tier_taint_kernel" in f.key]
    assert taint and "'tier'" in taint[0].message, \
        [f.message for f in findings]
    assert not [f for f in findings
                if "tier_clean_kernel" in f.key], \
        [f.message for f in findings]


def test_contract_live_tier_gates_registered():
    """The hot-tier gate is pinned by BOTH registries: every LiveTier
    hook tests `enabled` first (GatedFunction) and the ingest/search
    call sites are dominated by the gate read (GuardedCall) — the
    checker run over the real package enforces them; this test pins
    that the entries exist so a refactor cannot silently drop the
    noop contract."""
    from tempo_tpu.analysis.contracts import (GATED_FUNCTIONS,
                                              GUARDED_CALLS)

    gated = {(g.qualname, g.knob) for g in GATED_FUNCTIONS}
    for hook in ("absorb", "mark_cut", "mark_poll_visible",
                 "poll_visible", "search", "subscribe", "unsubscribe",
                 "has_subscribers", "notify_push"):
        assert (f"LiveTier.{hook}", "search_live_tier_enabled") in gated
    guarded = {(m, g.knob) for g in GUARDED_CALLS for m in g.methods}
    for m in ("absorb", "mark_cut", "search", "mark_poll_visible",
              "subscribe", "unsubscribe", "notify_push"):
        assert (m, "search_live_tier_enabled") in guarded


def test_contract_new_structural_gates_registered():
    """The stacking and sharding gates are pinned by BOTH registries:
    the gate functions test their attribute first (GatedFunction) and
    every call site is dominated by the gate read (GuardedCall) — the
    checker run over the real package (test_suite_clean_over_package)
    enforces them; this test pins that the entries exist so a refactor
    cannot silently drop the contract."""
    from tempo_tpu.analysis.contracts import (GATED_FUNCTIONS,
                                              GUARDED_CALLS)

    gated = {(g.qualname, g.knob) for g in GATED_FUNCTIONS}
    assert ("StructuralGate.stack_group_key",
            "search_structural_stack_enabled") in gated
    assert ("StructuralGate.shard_span_segment",
            "search_structural_shard_spans") in gated
    assert ("StructuralGate.bucket_group_key",
            "search_structural_bucket_enabled") in gated
    assert ("StructuralGate.remainder_pad",
            "search_structural_remainder_pages") in gated
    guarded = {(m, g.knob) for g in GUARDED_CALLS for m in g.methods}
    assert ("stack_group_key",
            "search_structural_stack_enabled") in guarded
    assert ("shard_span_segment",
            "search_structural_shard_spans") in guarded
    assert ("remainder_pad",
            "search_structural_remainder_pages") in guarded


def test_contract_selftrace_gates_registered():
    """The dogfood gate is pinned by BOTH registries: the lowering /
    annotation / recorder entry points test their gate attribute first
    (GatedFunction) and the hot-path call sites are dominated by the
    one-attribute gate read (GuardedCall) — the checker run over the
    real package enforces them; this pins that the entries exist so a
    refactor cannot silently drop the noop contract."""
    from tempo_tpu.analysis.contracts import (GATED_FUNCTIONS,
                                              GUARDED_CALLS)

    gated = {(g.qualname, g.knob) for g in GATED_FUNCTIONS}
    assert ("SelfTraceGate.lower_dispatch",
            "selftrace_ingest_enabled") in gated
    assert ("SelfTraceGate.annotate_query",
            "selftrace_ingest_enabled") in gated
    assert ("FlightRecorder.record",
            "selftrace_ingest_enabled") in gated
    guarded = {(m, g.knob) for g in GUARDED_CALLS for m in g.methods}
    assert ("lower_dispatch", "selftrace_ingest_enabled") in guarded
    assert ("annotate_query", "selftrace_ingest_enabled") in guarded
    assert ("record", "selftrace_ingest_enabled") in guarded


def test_jit_purity_clean_on_real_kernels(real_pkg):
    assert JitPurityChecker().check(real_pkg) == []


# ------------------------------------------------- metrics-catalog


_FIXTURE_METRIC_CATALOG = {
    "tempo_fixture_good_total": frozenset({"tenant"}),
}


def test_metrics_catalog_flags_uncatalogued_metric(bad_pkg):
    from tempo_tpu.analysis.metrics_catalog import MetricsCatalogChecker

    findings = MetricsCatalogChecker(
        catalog=_FIXTURE_METRIC_CATALOG).check(bad_pkg)
    missing = [f for f in findings if f.key.startswith("uncatalogued:")]
    assert len(missing) == 1, [f.message for f in findings]
    assert "tempo_fixture_missing_total" in missing[0].message


def test_metrics_catalog_flags_unknown_label_and_spares_twins(bad_pkg):
    from tempo_tpu.analysis.metrics_catalog import MetricsCatalogChecker

    findings = MetricsCatalogChecker(
        catalog=_FIXTURE_METRIC_CATALOG).check(bad_pkg)
    labels = [f for f in findings if f.key.startswith("unknown-label:")]
    assert len(labels) == 1, [f.message for f in findings]
    assert "'shard'" in labels[0].message
    # the clean twin (catalogued label only) and the dynamic
    # **expansion (not statically checkable) stay silent
    lines = {f.line for f in labels}
    src = bad_pkg.by_rel["analysis_bad/metrics_drift.py"].source
    for needle in ("good_metric.inc(tenant=\"t1\")",
                   "good_metric.inc(**labels)"):
        ok_line = src[:src.index(needle)].count("\n") + 1
        assert ok_line not in lines


def test_metrics_catalog_parses_doc_tables():
    from tempo_tpu.analysis.metrics_catalog import parse_doc_catalog

    doc = (
        "| name | type | labels | meaning |\n"
        "|---|---|---|---|\n"
        "| `tempo_a_total` | counter | `tenant`, `reason` | things |\n"
        "| `tempo_b` | gauge | — | a gauge |\n"
        "| `stage` | other | `x` | not a metric row (bad type) |\n"
        "| unticked | counter | `x` | not a metric row (no ticks) |\n")
    cat = parse_doc_catalog(doc)
    assert cat == {"tempo_a_total": frozenset({"tenant", "reason"}),
                   "tempo_b": frozenset()}


def test_metrics_catalog_clean_on_real_package(real_pkg):
    """Every registered metric has a docs/observability.md row and every
    literal write-site label is catalogued — the satellite contract."""
    from tempo_tpu.analysis.metrics_catalog import MetricsCatalogChecker

    assert MetricsCatalogChecker().check(real_pkg) == []


# ------------------------------------------------- allowlist semantics


def test_stale_allowlist_entry_fails_suite(bad_pkg, tmp_path):
    p = tmp_path / "allow.toml"
    p.write_text(
        '[[allow]]\n'
        'fingerprint = "lock-order:nowhere.py:000000000000"\n'
        'justification = "this defect was fixed long ago"\n')
    report = run_suite(bad_pkg, [LockOrderChecker()], load_allowlist(str(p)))
    assert len(report.stale) == 1
    assert report.exit_code == 1
    assert "matches no current finding" in report.stale[0].message


def test_allowlisted_finding_is_split_out(bad_pkg, tmp_path):
    findings = LockOrderChecker().check(bad_pkg)
    fp = next(f for f in findings
              if f.key.startswith("blocking:")).fingerprint
    p = tmp_path / "allow.toml"
    p.write_text(
        f'[[allow]]\nfingerprint = "{fp}"\n'
        'justification = "fixture: exercised by the self-tests"\n')
    report = run_suite(bad_pkg, [LockOrderChecker()], load_allowlist(str(p)))
    assert not report.stale
    assert len(report.allowlisted) == 1
    assert all(f.fingerprint != fp for f in report.findings)


def test_allowlist_requires_justification(tmp_path):
    with pytest.raises(AllowlistError):
        _parse_subset('[[allow]]\nfingerprint = "x:y:z"\n', "t")
    with pytest.raises(AllowlistError):
        _parse_subset('[[allow]]\nfingerprint = "x:y:z"\n'
                      'justification = ""\n', "t")


def test_fingerprint_survives_line_drift():
    a = Finding(checker="c", path="p.py", line=10, message="m",
                key="blocking:f:lock:.result")
    b = Finding(checker="c", path="p.py", line=99, message="m2",
                key="blocking:f:lock:.result")
    assert a.fingerprint == b.fingerprint
    c = Finding(checker="c", path="p.py", line=10, message="m",
                key="blocking:g:lock:.result")
    assert a.fingerprint != c.fingerprint


# ------------------------------------------------- CLI / CI surface


def test_check_cli_clean_exit_zero(capsys):
    sys.path.insert(0, os.path.join(_ROOT, "scripts"))
    try:
        import check
    finally:
        sys.path.pop(0)
    rc = check.main([])
    assert rc == 0
    assert "0 finding(s)" in capsys.readouterr().out


def test_check_cli_json_and_failure_exit(capsys, tmp_path):
    sys.path.insert(0, os.path.join(_ROOT, "scripts"))
    try:
        import check
    finally:
        sys.path.pop(0)
    bad = os.path.join(_FIXTURES, "analysis_bad")
    rc = check.main([bad, "--json", "--allowlist", "none",
                     "--checker", "lock-order"])
    out = capsys.readouterr().out
    assert rc == 1
    doc = json.loads(out)
    assert doc["ok"] is False
    # 2 blocking + 1 reacquire + 1 cycle (SCC) over the lock fixtures
    assert len(doc["findings"]) == 4
    f0 = doc["findings"][0]
    assert set(f0) == {"checker", "path", "line", "message", "hint",
                       "fingerprint"}
    # usage errors are exit 2, not 1 (CI must tell them apart)
    assert check.main(["/no/such/dir"]) == 2
    assert check.main(["--checker", "no-such-checker"]) == 2


# ------------------------------------------------- mypy strict subset


def test_mypy_strict_core_subset():
    """mypy --strict over the annotated core (robustness/, utils/,
    observability/metrics.py) using the pyproject [tool.mypy] block.
    Skipped when mypy isn't installed — the container bakes no new
    dependencies, but the config + annotations ship regardless."""
    pytest.importorskip("mypy")
    out = subprocess.run(
        [sys.executable, "-m", "mypy", "--config-file",
         os.path.join(_ROOT, "pyproject.toml")],
        cwd=_ROOT, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, f"mypy --strict failed:\n{out.stdout}"
