"""Deterministic tier-1 chaos suite (ISSUE 9): the fault-injection
harness drives every faultpoint through the REAL serving path and
asserts the robustness layer's contracts —

  - breaker lifecycle: trip at threshold, open routes host with ZERO
    device attempts, half-open probe recovers;
  - hang → watchdog timeout → host fallback, byte-identical vs an
    uninjected run, within the request deadline;
  - coalesced in-flight futures resubmit member queries on host;
  - deadline propagation through a sharded frontend query (partial
    answer, never a hang);
  - disarmed-noop byte identity (breaker off + faults disarmed runs the
    historical inline path);
  - docs drift: every faultpoint and every robustness knob documented.

Byte-identity canon: `device_seconds` is measured wall time and
`inspected_bytes_device` moves to the host side under fallback BY
DESIGN (the placement split must tell the truth), so identity is
asserted on the canonical response — traces + the deterministic
metrics — exactly the determinism stance the frontend takes by zeroing
device_seconds on external responses.
"""

from __future__ import annotations

import json
import os
import time

import pytest

from tempo_tpu import robustness, tempopb
from tempo_tpu.backend.local import LocalBackend
from tempo_tpu.backend.types import (
    BlockMeta,
    NAME_SEARCH,
    NAME_SEARCH_HEADER,
)
from tempo_tpu.db import TempoDB, TempoDBConfig
from tempo_tpu.encoding.v2.compression import compress
from tempo_tpu.observability import metrics as obs
from tempo_tpu.robustness.breaker import CLOSED, HALF_OPEN, OPEN
from tempo_tpu.robustness.faults import CATALOG
from tempo_tpu.search.columnar import ColumnarPages, PageGeometry

import numpy as np

_ROOT = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture(autouse=True)
def _clean_robustness():
    """Every test starts closed/disarmed and leaves no armed faultpoint
    or tripped breaker behind for the rest of the suite."""
    robustness.FAULTS.disarm_all()
    robustness.BREAKER.reset()
    robustness.BREAKER.enabled = True
    robustness.BREAKER.threshold = 3
    robustness.BREAKER.window_s = 30.0
    robustness.BREAKER.cooldown_s = 5.0
    robustness.GUARD.timeout_s = 30.0
    robustness.GUARD.lock_timeout_s = 60.0
    yield
    robustness.FAULTS.disarm_all()
    robustness.BREAKER.reset()
    robustness.BREAKER.enabled = True
    robustness.GUARD.timeout_s = 30.0
    robustness.GUARD.lock_timeout_s = 60.0


def _corpus(n_entries: int, seed: int,
            extra_vals: tuple = ()) -> ColumnarPages:
    """Small corpus with UNIQUE start seconds: top-k tie ordering at the
    k boundary is the one documented divergence between kernel variants
    (masked_topk docstring), and the identity assertions here are about
    the control plane, not tie arbitration."""
    rng = np.random.default_rng(seed)
    E, C = 256, 4
    P = -(-n_entries // E)
    key_dict = sorted(["service.name", "http.status_code"])
    services = [f"svc-{i:02d}" for i in range(8)]
    statuses = ["200", "500"]
    val_dict = sorted(set(services + statuses + list(extra_vals)))
    vidx = {v: i for i, v in enumerate(val_dict)}
    kv_key = np.full((P, E, C), -1, dtype=np.int32)
    kv_val = np.full((P, E, C), -1, dtype=np.int32)
    svc = rng.integers(0, len(services), size=(P, E))
    st = rng.integers(0, len(statuses), size=(P, E))
    kv_key[:, :, 0] = key_dict.index("service.name")
    kv_val[:, :, 0] = np.array(
        [vidx[s] for s in services], dtype=np.int32)[svc]
    kv_key[:, :, 1] = key_dict.index("http.status_code")
    kv_val[:, :, 1] = np.array(
        [vidx[s] for s in statuses], dtype=np.int32)[st]
    # unique, shuffled start seconds
    starts = rng.permutation(P * E).astype(np.uint32).reshape(P, E) + 1000
    durs = rng.integers(1, 5000, size=(P, E)).astype(np.uint32)
    valid = np.zeros((P, E), dtype=bool)
    flat = np.arange(P * E).reshape(P, E)
    valid[flat < n_entries] = True
    trace_ids = rng.integers(0, 255, size=(P, E, 16), dtype=np.uint8)
    return ColumnarPages(
        geometry=PageGeometry(entries_per_page=E, kv_per_entry=C),
        key_dict=key_dict, val_dict=val_dict,
        kv_key=kv_key, kv_val=kv_val,
        entry_start=starts, entry_end=starts + durs // 1000 + 1,
        entry_dur=durs, entry_valid=valid, trace_ids=trace_ids,
        entry_root_svc=np.full((P, E), -1, dtype=np.int32),
        entry_root_name=np.full((P, E), -1, dtype=np.int32),
        n_entries=n_entries,
        header={"n_entries": n_entries, "n_pages": P,
                "entries_per_page": E, "kv_per_entry": C},
    )


def _mkdb(tmp_path, n_blocks: int = 4, n_entries: int = 4096,
          **cfg_kw) -> TempoDB:
    cfg_kw.setdefault("auto_mesh", False)
    be = LocalBackend(str(tmp_path / "blocks"))
    db = TempoDB(be, str(tmp_path / "wal"), TempoDBConfig(**cfg_kw))
    metas = []
    for s in range(n_blocks):
        pages = _corpus(n_entries, seed=100 + s)
        m = BlockMeta(tenant_id="t", encoding="none")
        blob = compress(pages.to_bytes(), "none")
        hdr = dict(pages.header)
        hdr["encoding"] = "none"
        hdr["compressed_size"] = len(blob)
        be.write("t", m.block_id, NAME_SEARCH, blob)
        be.write("t", m.block_id, NAME_SEARCH_HEADER,
                 json.dumps(hdr).encode())
        metas.append(m)
    db.blocklist.update("t", add=metas)
    return db


def _req(limit: int = 50) -> tempopb.SearchRequest:
    req = tempopb.SearchRequest()
    req.tags["service.name"] = "svc-03"
    req.tags["http.status_code"] = "500"
    req.limit = limit
    return req


def _canon(resp: tempopb.SearchResponse) -> bytes:
    r = tempopb.SearchResponse()
    r.CopyFrom(resp)
    r.metrics.device_seconds = 0.0       # measured wall time
    r.metrics.inspected_bytes_device = 0  # placement moves under fallback
    return r.SerializeToString()


# ---------------------------------------------------------------- registry


def test_registry_arm_disarm_active_flag():
    F = robustness.FAULTS
    assert not F.active
    F.arm("poll_error", count=2)
    assert F.active
    F.disarm("poll_error")
    assert not F.active
    with pytest.raises(ValueError):
        F.arm("no_such_faultpoint")


def test_count_auto_disarm_and_fired_accounting():
    F = robustness.FAULTS
    F.arm("poll_error", count=2)
    for _ in range(2):
        with pytest.raises(robustness.InjectedFault):
            F.hit("poll_error")
    assert not F.active  # count exhausted -> auto-disarm
    F.hit("poll_error")  # disarmed: no-op
    assert F.snapshot()["fired_total"]["poll_error"] == 2


def test_spec_parsing_and_context_manager():
    F = robustness.FAULTS
    F.arm_spec("poll_error:count=1,p=1; flush_error:delay=0.01,raise=0")
    snap = F.snapshot()["armed"]
    assert snap["poll_error"]["count"] == 1
    assert snap["flush_error"]["delay_s"] == 0.01
    assert snap["flush_error"]["raises"] is False
    F.disarm_all()
    with F.armed("backend_read_error"):
        assert F.active
    assert not F.active


def test_probability_zero_never_fires():
    with robustness.FAULTS.armed("poll_error", probability=0.0):
        robustness.FAULTS.hit("poll_error")  # must not raise


# ------------------------------------------------------------ breaker unit


def test_breaker_lifecycle_trip_halfopen_recover():
    b = robustness.CircuitBreaker(threshold=2, window_s=10.0,
                                  cooldown_s=0.05, enabled=True)
    assert b.allow_device() and b.state == CLOSED
    b.record_fault("error", mode="batched")
    assert b.state == CLOSED
    b.record_fault("timeout", mode="batched")
    assert b.state == OPEN and b.blocking()
    assert not b.allow_device()          # open, cooldown not elapsed
    time.sleep(0.06)
    assert b.allow_device()              # half-open probe token granted
    assert b.state == HALF_OPEN
    assert not b.allow_device()          # probe tokens spent
    b.record_success()
    assert b.state == CLOSED and not b.blocking()
    assert b.snapshot()["transitions"]["half_open->closed"] == 1


def test_breaker_halfopen_fault_reopens():
    b = robustness.CircuitBreaker(threshold=1, cooldown_s=0.05,
                                  enabled=True)
    b.record_fault("timeout")
    time.sleep(0.06)
    assert b.allow_device()              # the recovery probe
    b.record_fault("timeout")            # ...fails
    assert b.state == OPEN
    assert not b.allow_device()          # cooldown restarted


def test_breaker_disabled_is_passthrough():
    b = robustness.CircuitBreaker(threshold=1, enabled=False)
    b.record_fault("error")
    assert b.allow_device() and not b.blocking() and b.state == CLOSED


def test_breaker_halfopen_token_regrant_after_silent_probe():
    """A granted probe token whose consumer never dispatches (its group
    pruned away, its request early-quit/deadlined) must not wedge the
    breaker in half-open forever: after another cooldown a new probe is
    granted."""
    b = robustness.CircuitBreaker(threshold=1, cooldown_s=0.05,
                                  enabled=True)
    b.record_fault("timeout")
    time.sleep(0.06)
    assert b.allow_device()       # probe token granted... and goes silent
    assert not b.allow_device()   # tokens spent, cooldown not elapsed
    time.sleep(0.06)
    assert b.allow_device()       # re-granted — recovery still possible
    b.record_success()
    assert b.state == CLOSED


# --------------------------------------------------- serving-path fallback


def test_dispatch_raise_falls_back_byte_identical(tmp_path):
    db = _mkdb(tmp_path)
    req = _req()
    base = _canon(db.search("t", req).response())
    robustness.BREAKER.reset()
    with robustness.FAULTS.armed("device_dispatch_raise", count=100):
        got = _canon(db.search("t", req).response())
    assert got == base
    assert obs.scan_dispatches.value(mode="host_fallback") >= 1
    assert obs.device_faults.value(kind="error", mode="batched") >= 1


def test_dispatch_hang_times_out_within_deadline(tmp_path):
    """The acceptance scenario: device_dispatch_hang mid-query → search
    returns byte-identical results via host fallback, bounded by the
    watchdog (no hung thread), breaker books the fault."""
    db = _mkdb(tmp_path)
    req = _req()
    base = _canon(db.search("t", req).response())
    robustness.BREAKER.reset()
    robustness.GUARD.timeout_s = 0.3
    faults0 = obs.device_faults.value(kind="timeout", mode="batched")
    with robustness.FAULTS.armed("device_dispatch_hang", delay_s=5.0,
                                 count=1):
        t0 = time.perf_counter()
        got = _canon(db.search("t", req).response())
        wall = time.perf_counter() - t0
    assert got == base
    assert wall < 3.0, f"hang leaked into the caller ({wall:.2f}s)"
    assert obs.device_faults.value(kind="timeout", mode="batched") \
        == faults0 + 1


def test_breaker_trips_and_open_routes_host_with_zero_dispatches(tmp_path):
    db = _mkdb(tmp_path)
    req = _req()
    base = _canon(db.search("t", req).response())
    robustness.BREAKER.reset()
    robustness.BREAKER.threshold = 3
    with robustness.FAULTS.armed("device_dispatch_raise", count=1000):
        for _ in range(3):
            assert _canon(db.search("t", req).response()) == base
        assert robustness.BREAKER.state == OPEN
        # while open, nothing reaches the (armed!) dispatch site
        fired0 = robustness.FAULTS.snapshot()["fired_total"][
            "device_dispatch_raise"]
        assert _canon(db.search("t", req).response()) == base
        assert robustness.FAULTS.snapshot()["fired_total"][
            "device_dispatch_raise"] == fired0
    assert robustness.BREAKER.state == OPEN


def test_breaker_recovers_through_half_open(tmp_path):
    db = _mkdb(tmp_path)
    req = _req()
    base = _canon(db.search("t", req).response())
    robustness.BREAKER.reset()
    robustness.BREAKER.threshold = 1
    robustness.BREAKER.cooldown_s = 0.05
    with robustness.FAULTS.armed("device_dispatch_raise", count=1):
        assert _canon(db.search("t", req).response()) == base
    assert robustness.BREAKER.state == OPEN
    time.sleep(0.06)  # cooldown elapses; fault is cleared (count=1)
    assert _canon(db.search("t", req).response()) == base
    snap = robustness.BREAKER.snapshot()
    assert snap["state"] == CLOSED
    assert snap["transitions"]["open->half_open"] == 1
    assert snap["transitions"]["half_open->closed"] == 1


def test_h2d_hang_host_routes_group(tmp_path):
    db = _mkdb(tmp_path)
    req = _req()
    base = _canon(db.search("t", req).response())
    db.batcher._cache.clear()          # force a re-stage
    db.batcher._cache_total = 0
    robustness.BREAKER.reset()
    robustness.GUARD.timeout_s = 0.3
    with robustness.FAULTS.armed("h2d_delay", delay_s=5.0, count=1):
        t0 = time.perf_counter()
        got = _canon(db.search("t", req).response())
        wall = time.perf_counter() - t0
    assert got == base
    assert wall < 3.0
    assert obs.device_faults.value(kind="timeout", mode="h2d") >= 1


def test_fallback_with_coalescer_disabled(tmp_path):
    """coalesce_max_queries <= 1 takes the DIRECT dispatch path — a
    DeviceFault there must host-fallback too, not fail the query."""
    db = _mkdb(tmp_path, search_coalesce_max_queries=1)
    req = _req()
    base = _canon(db.search("t", req).response())
    robustness.BREAKER.reset()
    with robustness.FAULTS.armed("device_dispatch_raise", count=100):
        got = _canon(db.search("t", req).response())
    assert got == base


def test_drain_resubmit_no_double_skip_count(tmp_path):
    """A dict-pruned block's skip is booked once by the main loop; the
    drain-time host resubmit must not book it again (skipped_blocks
    would inflate and break wedged-vs-healthy identity)."""
    be = LocalBackend(str(tmp_path / "blocks"))
    db = TempoDB(be, str(tmp_path / "wal"), TempoDBConfig(auto_mesh=False))
    for seed, extra in ((1, ("special-xyz",)), (2, ())):
        pages = _corpus(2048, seed=seed, extra_vals=extra)
        m = BlockMeta(tenant_id="t", encoding="none")
        blob = compress(pages.to_bytes(), "none")
        hdr = dict(pages.header)
        hdr["encoding"] = "none"
        hdr["compressed_size"] = len(blob)
        be.write("t", m.block_id, NAME_SEARCH, blob)
        be.write("t", m.block_id, NAME_SEARCH_HEADER,
                 json.dumps(hdr).encode())
        db.blocklist.update("t", add=[m])
    req = tempopb.SearchRequest()
    req.tags["service.name"] = "special-xyz"
    req.limit = 10
    healthy = db.search("t", req).response()
    # block 2's dictionary lacks the value: exactly one dict-prune
    assert healthy.metrics.skipped_blocks == 1
    robustness.BREAKER.reset()
    with robustness.FAULTS.armed("device_dispatch_raise", count=1):
        wedged = db.search("t", req).response()
    assert wedged.metrics.skipped_blocks == 1
    assert _canon(wedged) == _canon(healthy)


def test_single_block_path_host_fallback(tmp_path):
    """The SearchBlock/serverless path (BackendSearchBlock.search)
    honors the breaker and falls back byte-identically on DeviceFault."""
    db = _mkdb(tmp_path, n_blocks=1)
    m = db.blocklist.metas("t")[0]
    req = _req()
    bsb = db._search_block_for(m)
    base = bsb.search(req).response().SerializeToString()
    robustness.BREAKER.reset()
    with robustness.FAULTS.armed("device_dispatch_raise", count=100):
        got = bsb.search(req).response().SerializeToString()
    assert got == base
    # breaker forced open: host route, zero dispatch attempts
    for _ in range(3):
        robustness.BREAKER.record_fault("timeout")
    assert robustness.BREAKER.state == OPEN
    before = obs.scan_dispatches.value(mode="host_fallback")
    assert bsb.search(req).response().SerializeToString() == base
    assert obs.scan_dispatches.value(mode="host_fallback") > before


def test_coalesced_inflight_resubmits_members_on_host(tmp_path):
    """A fused multi-query dispatch that faults delivers DeviceFault to
    every member future; each member's drain resubmits ITS query on the
    host path — all answers stay byte-identical to serial."""
    import threading

    db = _mkdb(tmp_path, n_blocks=4,
               search_coalesce_window_s=0.05, search_coalesce_max_queries=4)
    reqs = []
    for i in range(4):
        r = tempopb.SearchRequest()
        r.tags["service.name"] = f"svc-{i:02d}"
        r.limit = 30
        reqs.append(r)
    serial = [_canon(db.search("t", r).response()) for r in reqs]
    robustness.BREAKER.reset()
    robustness.BREAKER.threshold = 100   # keep it closed: test the drain
    got = [None] * 4
    with robustness.FAULTS.armed("device_dispatch_raise", count=2):
        barrier = threading.Barrier(4)

        def worker(i):
            barrier.wait()
            got[i] = _canon(db.search("t", reqs[i]).response())

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
    assert got == serial


def test_deadline_propagates_through_sharded_frontend(tmp_path):
    """An expired request deadline makes a sharded frontend query come
    back PARTIAL (marked, counted) — fast — instead of stacking
    sub-queries behind a wedged device."""
    from tempo_tpu.modules.app import App, AppConfig
    from tempo_tpu.modules.frontend import FrontendConfig

    app = App(AppConfig(
        wal_dir=str(tmp_path / "wal"),
        db=TempoDBConfig(auto_mesh=False),
        frontend=FrontendConfig(query_shards=4)))
    tr = _trace_batches()
    app.push("t", tr)
    app.flush_tick(force=True)
    app.poll_tick()
    req = tempopb.SearchRequest()
    req.limit = 10
    # healthy: a generous deadline changes nothing
    with robustness.deadline.start(30.0):
        ok = app.search("t", req)
    assert not ok.metrics.partial
    # an already-expired deadline: partial, immediate
    before = obs.partial_results.value(reason="deadline")
    with robustness.deadline.start(1e-9):
        time.sleep(0.01)
        t0 = time.perf_counter()
        resp = app.search("t", req)
        wall = time.perf_counter() - t0
    assert resp.metrics.partial
    # never-started batches count FAILED: the client can see how much
    # of the corpus went unsearched, not just that "something" did
    assert resp.metrics.failed_blocks >= 1
    assert wall < 5.0
    assert obs.partial_results.value(reason="deadline") > before
    # trace-by-id honors the deadline too: returns fast with the
    # unsearched shards counted failed instead of hanging
    with robustness.deadline.start(1e-9):
        time.sleep(0.01)
        t0 = time.perf_counter()
        tr_resp = app.find_trace("t", b"\x01" * 16)
        wall = time.perf_counter() - t0
    assert wall < 5.0
    assert tr_resp.metrics.failed_blocks >= 1


def _trace_batches():
    from tempo_tpu.utils.test_data import make_trace

    return list(make_trace(trace_id=b"\x01" * 16).batches)


def test_batcher_deadline_stops_queueing(tmp_path):
    db = _mkdb(tmp_path, n_blocks=4)
    req = _req()
    db.search("t", req)  # warm
    with robustness.deadline.start(1e-9):
        time.sleep(0.01)
        resp = db.search("t", req).response()
    assert resp.metrics.partial
    assert resp.metrics.inspected_blocks == 0  # nothing dispatched


def test_replica_error_partial_results(tmp_path):
    from tempo_tpu.modules.app import App, AppConfig

    app = App(AppConfig(wal_dir=str(tmp_path / "wal"),
                        db=TempoDBConfig(auto_mesh=False)))
    app.push("t", _trace_batches())
    q = app.queriers[0]
    req = tempopb.SearchRequest()
    req.limit = 10
    before = obs.partial_results.value(reason="replica")
    with robustness.FAULTS.armed("replica_error", count=10):
        resp = q.search_recent("t", req)
    assert resp.metrics.partial
    assert resp.metrics.failed_blocks >= 1
    assert obs.partial_results.value(reason="replica") > before
    # partial-ness survives the frontend merge
    from tempo_tpu.search import SearchResults

    merged = SearchResults(limit=10)
    merged.merge_response(resp)
    assert merged.metrics.partial


def test_flush_error_books_retry_not_loss(tmp_path):
    from tempo_tpu.modules.app import App, AppConfig

    app = App(AppConfig(wal_dir=str(tmp_path / "wal"),
                        db=TempoDBConfig(auto_mesh=False)))
    app.push("t", _trace_batches())
    with robustness.FAULTS.armed("flush_error", count=1):
        completed = app.flush_tick(force=True)
    assert completed == []  # first attempt injected away
    ing = next(iter(app.ingesters.values()))
    meta = ing.instance("t").complete_one(ignore_backoff=True)
    assert meta is not None  # retry lands; nothing lost


def test_poll_error_and_backend_read_error_surface(tmp_path):
    db = _mkdb(tmp_path)
    with robustness.FAULTS.armed("poll_error", count=1), \
            pytest.raises(robustness.InjectedFault):
        db.poll()
    (tmp_path / "b2").mkdir()
    db2 = _mkdb(tmp_path / "b2", n_blocks=2)
    db2.search("t", _req())  # warm headers
    # cold headers + injected read error: the DIRECT path surfaces the
    # flake loudly (the partial-swallow lives at the querier/frontend
    # layer, where it books tempo_search_partial_results_total)
    db2._headers.clear()
    db2._search_blocks.clear()
    db2._jobs_cache.clear()
    db2.batcher._cache.clear()
    db2.batcher._cache_total = 0
    db2.batcher._host_cache.clear()
    db2.batcher._host_total = 0
    with robustness.FAULTS.armed("backend_read_error", count=1), \
            pytest.raises(robustness.InjectedFault):
        db2.search("t", _req())
    # next query (fault exhausted) is healthy again
    assert db2.search("t", _req()).response().metrics.inspected_blocks == 2


def test_dispatch_lock_timeout_books_breaker_fault():
    from tempo_tpu.parallel import mesh

    robustness.BREAKER.reset()
    robustness.GUARD.lock_timeout_s = 0.1
    before = obs.dispatch_lock_timeouts.value()
    acquired = mesh.dispatch_lock.acquire()
    try:
        with pytest.raises(robustness.DispatchLockTimeout):
            with mesh.locked_collective():
                pass
    finally:
        if acquired:
            mesh.dispatch_lock.release()
    assert obs.dispatch_lock_timeouts.value() == before + 1
    assert robustness.BREAKER.snapshot()["faults_in_window"] >= 1


def test_disarmed_noop_byte_identity(tmp_path):
    """The noop contract: breaker off + faults disarmed answers
    byte-identically to breaker on (healthy device) — the guard's
    worker hop changes nothing but placement of the wait."""
    db = _mkdb(tmp_path)
    req = _req()
    robustness.BREAKER.enabled = True
    on = _canon(db.search("t", req).response())
    robustness.BREAKER.enabled = False
    assert not robustness.GUARD.active
    off = _canon(db.search("t", req).response())
    assert on == off


def test_status_device_block_reads_breaker(tmp_path):
    from tempo_tpu.observability.profile import device_status

    robustness.BREAKER.reset()
    robustness.BREAKER.enabled = True
    d = device_status()
    assert d["breaker"]["state"] == CLOSED
    assert d["wedged"] is False
    robustness.BREAKER.record_fault("timeout")
    robustness.BREAKER.record_fault("timeout")
    robustness.BREAKER.record_fault("timeout")
    d = device_status()
    assert d["breaker"]["state"] == OPEN
    assert d["wedged"] is True


def test_debug_faults_route_json(tmp_path):
    """/debug/faults is covered by test_debug_routes' generic contract;
    here: the payload carries catalog + armed + breaker and is
    json-serializable with a faultpoint armed."""
    from tempo_tpu.api.http import HTTPApi

    class _App:
        pass

    api = HTTPApi(_App(), debug_endpoints=True)
    with robustness.FAULTS.armed("h2d_delay", delay_s=0.5):
        code, body = api._debug_faults_route({})
    assert code == 200
    doc = json.loads(json.dumps(body))
    assert "h2d_delay" in doc["faults"]["armed"]
    assert set(doc["faults"]["catalog"]) == set(CATALOG)
    assert doc["breaker"]["state"] in (CLOSED, OPEN, HALF_OPEN)


# ------------------------------------------------- owner-routed HBM chaos


@pytest.fixture()
def _clean_ownership():
    from tempo_tpu.search.ownership import OWNERSHIP

    OWNERSHIP.reset()
    yield OWNERSHIP
    OWNERSHIP.reset()


def test_chaos_owner_death_mid_query(tmp_path, _clean_ownership):
    """Owner death mid-query: the owner's querier dies between batches
    of one request (replica_error armed on the recent leg too); retries
    land on the surviving non-owner, which answers through the host
    route — byte-identical to the ownership-disabled path, PARTIAL only
    for the injected replica legs, never a hang."""
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier
    from tempo_tpu.modules.ring import Ring
    from tempo_tpu.search import ownership

    db = _mkdb(tmp_path, n_blocks=6, search_max_batch_pages=8)
    q = Querier(db, Ring(), {})

    class _Dying:
        def __init__(self, inner, die=False):
            self.inner = inner
            self.db = inner.db
            self.die = die
            self.calls = 0

        def search_recent(self, tenant, req):
            return self.inner.search_recent(tenant, req)

        def search_blocks(self, breq):
            self.calls += 1
            if self.die:
                raise RuntimeError("owner died mid-query")
            return self.inner.search_blocks(breq)

    owner = _Dying(q)
    peer = _Dying(q)
    fe = QueryFrontend([owner, peer], FrontendConfig(retries=3))
    req = _req(limit=10_000)
    # baseline: ownership disabled, everyone healthy, replica fault
    # armed identically (count high enough to cover both runs' legs)
    with robustness.FAULTS.armed("replica_error", count=1000):
        base = _canon(fe.search("t", req))
        ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                            groups=32)
        owner.die = True  # member 0's process is gone
        t0 = time.perf_counter()
        got = _canon(fe.search("t", req))
        wall = time.perf_counter() - t0
    assert got == base
    assert owner.calls >= 1  # the owner WAS tried first
    assert wall < 30.0


def test_chaos_wedged_owner_breaker_to_host_route(tmp_path,
                                                  _clean_ownership):
    """A wedged owner: its device dispatches hang, the watchdog faults
    them, the breaker opens, and every owned group degrades to the host
    route — byte-identical to the ownership-disabled uninjected run and
    bounded by the watchdog, with device_dispatch_hang armed."""
    from tempo_tpu.search import ownership

    db = _mkdb(tmp_path, n_blocks=6, search_max_batch_pages=8)
    req = _req(limit=10_000)
    base = _canon(db.search("t", req).response())
    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=32)
    robustness.BREAKER.reset()
    robustness.GUARD.timeout_s = 0.3
    with robustness.FAULTS.armed("device_dispatch_hang", delay_s=5.0,
                                 count=1000):
        t0 = time.perf_counter()
        got = _canon(db.search("t", req).response())
        wall = time.perf_counter() - t0
    assert got == base
    assert wall < 10.0  # watchdog-bounded, never the 5s hang per group
    # the wedge tripped the breaker; the non-owner share host-routed
    assert robustness.BREAKER.snapshot()["faults_in_window"] >= 1
    # and with the breaker now open: still byte-identical, zero device
    for _ in range(3):
        robustness.BREAKER.record_fault("timeout")
    assert robustness.BREAKER.state == OPEN
    assert _canon(db.search("t", req).response()) == base


def test_chaos_rebalance_under_load_4way(tmp_path, _clean_ownership):
    """Rebalance under load: 4 concurrent searchers while membership
    flips repeatedly — every answer byte-identical to the
    ownership-disabled path, deferred evictions keep the HBM accounting
    non-negative, and nothing hangs."""
    import threading

    from tempo_tpu.search import ownership

    db = _mkdb(tmp_path, n_blocks=6, search_max_batch_pages=8)
    reqs = []
    for i in range(4):
        r = tempopb.SearchRequest()
        r.tags["service.name"] = f"svc-{i:02d}"
        r.limit = 10_000
        reqs.append(r)
    serial = [_canon(db.search("t", r).response()) for r in reqs]
    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=32)
    stop = threading.Event()
    errors: list = []

    def searcher(i):
        while not stop.is_set():
            try:
                got = _canon(db.search("t", reqs[i]).response())
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)
                return
            if got != serial[i]:
                errors.append(AssertionError(
                    f"query {i} diverged mid-rebalance"))
                return

    ts = [threading.Thread(target=searcher, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    memberships = (["m0"], ["m0", "m1"], ["m0", "m1", "m2"],
                   ["m1", "m0"], ["m0", "m1"])
    for round_ in range(3):
        for ms in memberships:
            db.rebalance_ownership(list(ms), self_id="m0",
                                   prestage=False)
            time.sleep(0.02)
    stop.set()
    for t in ts:
        t.join(timeout=60)
        assert not t.is_alive(), "searcher hung across rebalances"
    assert not errors, errors[:1]
    # accounting survived the churn: totals never went negative and a
    # final unpinned sweep leaves a consistent cache
    b = db.batcher
    with b._lock:
        b._run_deferred_evictions_locked()
        assert b._cache_total >= 0
        assert b._cache_total == sum(e.nbytes for e in b._cache.values())


# --------------------------------------- replicated ownership + hedging


def test_chaos_primary_death_mid_hedge(tmp_path, _clean_ownership):
    """Primary death MID-HEDGE: the promoted group's primary wedges
    past the hedge delay and then dies; the hedge already fired at the
    replica, the replica's answer wins, and the response stays
    byte-identical — the primary's late failure is swallowed by the
    race, never surfaced."""
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier
    from tempo_tpu.modules.ring import Ring
    from tempo_tpu.search import ownership
    from tempo_tpu.search.ownership import OWNERSHIP

    db = _mkdb(tmp_path, n_blocks=6, search_max_batch_pages=8)
    q = Querier(db, Ring(), {})

    class _DyingSlow:
        def __init__(self, inner):
            self.inner = inner
            self.db = inner.db
            self.wedged = False

        def search_recent(self, tenant, req):
            return self.inner.search_recent(tenant, req)

        def search_blocks(self, breq):
            if self.wedged:
                time.sleep(0.2)  # past the 20 ms hedge delay...
                raise RuntimeError("primary died mid-hedge")
            return self.inner.search_blocks(breq)

    primary, replica = _DyingSlow(q), _DyingSlow(q)
    fe = QueryFrontend([primary, replica], FrontendConfig(retries=3))
    req = _req(limit=10_000)
    base = _canon(fe.search("t", req))
    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=32, rf=2, hot_rate=0.01,
                        hedge_delay_ms=20)
    # one access per block promotes every group past the tiny threshold
    for m in db.blocklist.metas("t"):
        OWNERSHIP.record_access(m.block_id)
    won0 = obs.hedged_dispatches.value(result="hedge_won")
    primary.wedged = True  # member 0's process wedges, then dies
    t0 = time.perf_counter()
    got = _canon(fe.search("t", req))
    wall = time.perf_counter() - t0
    assert got == base
    assert wall < 30.0
    batches = fe._search_batches("t")
    if any(b[2] == 0 for b in batches):  # some group owned by m0
        assert obs.hedged_dispatches.value(result="hedge_won") > won0


def test_chaos_both_replicas_wedged_breaker_host_route(
        tmp_path, _clean_ownership):
    """Both replicas of every promoted group wedge at the device (the
    shared device dispatch hangs): the watchdog faults the dispatches,
    the breaker opens, every group — replicated or not — degrades to
    the host route, byte-identical and bounded by the watchdog."""
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier
    from tempo_tpu.modules.ring import Ring
    from tempo_tpu.search import ownership
    from tempo_tpu.search.ownership import OWNERSHIP

    db = _mkdb(tmp_path, n_blocks=6, search_max_batch_pages=8)
    q = Querier(db, Ring(), {})
    fe = QueryFrontend([q, q], FrontendConfig(retries=3))
    req = _req(limit=10_000)
    base = _canon(fe.search("t", req))
    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=32, rf=2, hot_rate=0.01,
                        hedge_delay_ms=10)
    for m in db.blocklist.metas("t"):
        OWNERSHIP.record_access(m.block_id)
    robustness.BREAKER.reset()
    robustness.GUARD.timeout_s = 0.3
    with robustness.FAULTS.armed("device_dispatch_hang", delay_s=5.0,
                                 count=1000):
        t0 = time.perf_counter()
        got = _canon(fe.search("t", req))
        wall = time.perf_counter() - t0
    assert got == base
    assert wall < 30.0  # watchdog-bounded, never a hang per attempt
    assert robustness.BREAKER.snapshot()["faults_in_window"] >= 1
    # breaker now forced open: still byte-identical, zero device
    for _ in range(3):
        robustness.BREAKER.record_fault("timeout")
    assert robustness.BREAKER.state == OPEN
    assert _canon(fe.search("t", req)) == base


def test_chaos_promotion_flapping_residency_conserved(
        tmp_path, _clean_ownership):
    """Promotion/demotion flapping under concurrent searchers: a
    background thread force-demotes every promoted group (far-future
    sweep) while the serving loop's heat feed re-promotes on each scan
    — every answer stays byte-identical and the HBM accounting never
    goes negative (cache total == sum of entries)."""
    import threading

    from tempo_tpu.search import ownership
    from tempo_tpu.search.ownership import OWNERSHIP

    db = _mkdb(tmp_path, n_blocks=4, search_max_batch_pages=8)
    req = _req(limit=10_000)
    base = _canon(db.search("t", req).response())
    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=32, rf=2, hot_rate=0.02)
    stop = threading.Event()
    errors: list = []

    def searcher():
        while not stop.is_set():
            try:
                got = _canon(db.search("t", req).response())
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)
                return
            if got != base:
                errors.append(AssertionError("diverged mid-flap"))
                return

    def flapper():
        while not stop.is_set():
            # far-future decay: every promoted group demotes, firing
            # the TempoDB hook's residency rebalance in background;
            # the next scan's record_access promotes again
            OWNERSHIP.sweep(now=time.monotonic() + 600.0)
            time.sleep(0.005)

    ts = [threading.Thread(target=searcher) for _ in range(3)]
    ts.append(threading.Thread(target=flapper))
    for t in ts:
        t.start()
    time.sleep(1.5)
    stop.set()
    for t in ts:
        t.join(timeout=60)
        assert not t.is_alive(), "hung under promotion flapping"
    assert not errors, errors[:1]
    up = obs.hbm_replica_promotions.value(dir="up")
    down = obs.hbm_replica_promotions.value(dir="down")
    assert up >= 1 and down >= 1  # it really flapped
    b = db.batcher
    with b._lock:
        b._run_deferred_evictions_locked()
        assert b._cache_total >= 0
        assert b._cache_total == sum(e.nbytes for e in b._cache.values())
    assert _canon(db.search("t", req).response()) == base


# ----------------------------------------------------------- docs drift


def test_faultpoint_catalog_documented():
    """Every registered faultpoint must appear in docs/robustness.md —
    the faultpoint twin of test_config_docs.py. Thin wrapper over the
    analysis drift engine's "faultpoints" catalog (same invariant the
    hand-rolled pre-PR-10 version enforced)."""
    from tempo_tpu.analysis.drift import catalog_findings

    findings = catalog_findings("faultpoints")
    assert not findings, (
        "faultpoints missing from docs/robustness.md catalog:\n"
        + "\n".join(f"{f.path}:{f.line}: {f.message}" for f in findings))


def test_robustness_knobs_documented():
    """Every robustness TempoDBConfig knob (search_breaker_*,
    search_*_timeout_s, robustness_*) must appear in both
    docs/robustness.md and docs/configuration.md — drift-engine
    catalog "robustness-knobs"."""
    from tempo_tpu.analysis.drift import catalog_findings

    findings = catalog_findings("robustness-knobs")
    assert not findings, (
        "robustness knobs missing from docs/robustness.md or "
        "docs/configuration.md:\n"
        + "\n".join(f"{f.path}:{f.line}: {f.message}" for f in findings))
