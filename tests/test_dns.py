"""DNS discovery: wire codec, resolver, spec expansion, memberlist join.

Covers the reference's thanos-DNS-provider role (memberlist join +
worker→frontend discovery) against a protocol-faithful in-process UDP
DNS server with name compression and SRV glue records.
"""

from __future__ import annotations

import struct

import pytest

from tempo_tpu.utils.dns import (
    TYPE_A,
    TYPE_SRV,
    Resolver,
    encode_query,
    parse_response,
)

from tests.fake_dns import FakeDNSServer

ZONE = {
    ("ingest.example.org", TYPE_A): ["10.0.0.1", "10.0.0.2"],
    ("_gossip._tcp.example.org", TYPE_SRV): [
        (0, 50, 7946, "node-a.example.org"),
        (0, 50, 7947, "node-b.example.org"),
    ],
    ("node-a.example.org", TYPE_A): ["10.1.0.1"],
    ("node-b.example.org", TYPE_A): ["10.1.0.2"],
}


@pytest.fixture()
def dns():
    s = FakeDNSServer(ZONE).start()
    yield s
    s.stop()


def _resolver(dns, **kw):
    return Resolver(nameserver=dns.addr, timeout_s=1.0, retries=0, **kw)


def test_a_lookup_wire(dns):
    r = _resolver(dns)
    recs = r.query("ingest.example.org", TYPE_A)
    assert sorted(p for _, _, _, p in recs) == ["10.0.0.1", "10.0.0.2"]


def test_srv_lookup_with_compression_and_glue(dns):
    r = _resolver(dns)
    recs = r.query("_gossip._tcp.example.org", TYPE_SRV)
    assert sorted(p[3] for _, _, _, p in recs) == [
        "node-a.example.org",
        "node-b.example.org",
    ]
    # glue A records landed in the cache: target resolution needs no
    # extra query round-trip
    n_queries = len(dns.queries)
    assert [p for _, _, _, p in r.query("node-a.example.org", TYPE_A)] == ["10.1.0.1"]
    assert len(dns.queries) == n_queries


def test_resolve_specs(dns):
    r = _resolver(dns)
    assert r.resolve_spec("1.2.3.4:7946") == ["1.2.3.4:7946"]
    assert r.resolve_spec("dns+ingest.example.org:7946") == [
        "10.0.0.1:7946",
        "10.0.0.2:7946",
    ]
    assert r.resolve_spec("dnssrv+_gossip._tcp.example.org") == [
        "10.1.0.1:7946",
        "10.1.0.2:7947",
    ]


def test_resolve_all_skips_failures(dns):
    r = _resolver(dns)
    out = r.resolve_all(
        ["dns+nope.example.org:1", "dns+ingest.example.org:9", "static:5"]
    )
    assert out == ["10.0.0.1:9", "10.0.0.2:9", "static:5"]


def test_cache_and_stale_on_error(dns):
    r = _resolver(dns)
    first = r.resolve_spec("dns+ingest.example.org:7946")
    n = len(dns.queries)
    assert r.resolve_spec("dns+ingest.example.org:7946") == first  # cached
    assert len(dns.queries) == n
    # server dies → TTL expires → stale answer still served
    dns.stop()
    with r._lock:
        r._cache = {k: (0.0, v[1]) for k, v in r._cache.items()}  # expire all
    assert r.resolve_spec("dns+ingest.example.org:7946") == first


def test_nxdomain_returns_empty(dns):
    r = _resolver(dns)
    # NXDOMAIN parses as an empty answer set → empty result, not a crash
    assert r.resolve_spec("dnssrv+_missing._tcp.example.org") == []
    assert r.resolve_spec("dns+missing.example.org:1") == []


def test_srv_root_target_skipped(dns):
    dns.zone[("_mixed._tcp.example.org", TYPE_SRV)] = [
        (0, 0, 7946, "node-a.example.org"),
        (0, 0, 0, "."),  # RFC 2782: service decidedly unavailable
    ]
    r = _resolver(dns)
    assert r.resolve_spec("dnssrv+_mixed._tcp.example.org") == ["10.1.0.1:7946"]


def test_validate_spec_rejects_bad_labels():
    from tempo_tpu.utils.dns import validate_spec

    with pytest.raises(ValueError, match="label"):
        validate_spec("dns+gossip..svc:7946")  # empty label
    with pytest.raises(ValueError, match="label"):
        validate_spec("dnssrv+_g._tcp." + "x" * 70 + ".org")
    validate_spec("dns+gossip.svc:7946")  # fine


def test_stale_served_fast_while_dns_down(dns):
    import time as _t

    r = Resolver(nameserver=dns.addr, timeout_s=0.5, retries=0, neg_ttl_s=30.0)
    first = r.resolve_spec("dns+ingest.example.org:7946")
    dns.stop()
    with r._lock:  # expire the positive entries
        r._cache = {k: (0.0, v[1]) for k, v in r._cache.items()}
    # first post-outage call pays one timeout and serves stale
    assert r.resolve_spec("dns+ingest.example.org:7946") == first
    # second call is negative-cached: stale served with NO wire wait
    t0 = _t.monotonic()
    assert r.resolve_spec("dns+ingest.example.org:7946") == first
    assert _t.monotonic() - t0 < 0.25


def test_malformed_packet_raises_valueerror_not_struct_error(dns):
    # header promises records the packet doesn't contain — must surface
    # as ValueError (struct.error would kill the gossip thread)
    hdr = struct.pack(">HHHHHH", 7, 0x8180, 0, 3, 0, 0)
    with pytest.raises(ValueError):
        parse_response(hdr + b"\x00\x00\x01", 7)


def test_negative_cache_fast_fails(dns):
    r = Resolver(nameserver=("127.0.0.1", 1), timeout_s=0.05, retries=0,
                 neg_ttl_s=30.0)
    import time as _t

    t0 = _t.monotonic()
    with pytest.raises(OSError):
        r.query("x.example.org", TYPE_A)
    first = _t.monotonic() - t0
    t0 = _t.monotonic()
    with pytest.raises(OSError):  # negative-cached: no network wait
        r.query("x.example.org", TYPE_A)
    assert _t.monotonic() - t0 < first


def test_malformed_join_spec_fails_at_construction():
    from tempo_tpu.modules.membership import Memberlist

    with pytest.raises(ValueError, match="host:port"):
        Memberlist("x", "querier", bind="127.0.0.1:0",
                   join=["dns+gossip.svc"])  # missing :port
    with pytest.raises(ValueError, match="SRV"):
        Memberlist("x", "querier", bind="127.0.0.1:0",
                   join=["dnssrv+_svc._tcp.local:7946"])  # port not allowed


def test_txid_mismatch_rejected():
    q = encode_query("x.example.org", TYPE_A, 42)
    resp = struct.pack(">HHHHHH", 43, 0x8180, 0, 0, 0, 0)
    with pytest.raises(ValueError, match="transaction"):
        parse_response(resp, 42)
    assert q[:2] == struct.pack(">H", 42)


def test_compression_pointer_loop_rejected():
    # name at offset 12 pointing at itself
    hdr = struct.pack(">HHHHHH", 1, 0x8180, 0, 1, 0, 0)
    loop = struct.pack(">H", 0xC00C)
    msg = hdr + loop + struct.pack(">HHIH", TYPE_A, 1, 5, 4) + b"\x01\x02\x03\x04"
    with pytest.raises(ValueError):
        parse_response(msg, 1)


def test_memberlist_dns_join(dns):
    """Two memberlists converge when the seed is a dnssrv+ spec whose SRV
    targets resolve to the real gossip listener."""
    import time

    from tempo_tpu.modules.membership import Memberlist

    a = Memberlist("node-a", "ingester", bind="127.0.0.1:0")
    host, port = a.gossip_addr.rsplit(":", 1)
    # zone entry pointing at a's real listener
    dns.zone[("_tempo._tcp.local", TYPE_SRV)] = [(0, 0, int(port), "a.local")]
    dns.zone[("a.local", TYPE_A)] = [host]
    b = Memberlist(
        "node-b", "querier", bind="127.0.0.1:0",
        join=["dnssrv+_tempo._tcp.local"],
        resolver=_resolver(dns),
    )
    try:
        deadline = time.time() + 10
        ids_a = ids_b = set()
        while time.time() < deadline:
            b.tick()
            a.tick()
            ids_b = {m.id for m in b.members()}
            ids_a = {m.id for m in a.members()}
            if "node-a" in ids_b and "node-b" in ids_a:
                break
            time.sleep(0.05)
        assert "node-a" in ids_b and "node-b" in ids_a
    finally:
        a.shutdown()
        b.shutdown()


def test_truncated_udp_falls_back_to_tcp():
    """A TC-flagged UDP answer (large SRV sets pass 512 bytes in real
    clusters) must retry over TCP and return the FULL record set —
    previously discovery silently shrank to the truncated answer
    (ADVICE r1 #4)."""
    zone = {("big.example.org", TYPE_A): [f"10.9.{i}.1" for i in range(40)]}
    s = FakeDNSServer(zone, udp_limit=100).start()
    try:
        r = Resolver(nameserver=s.addr, timeout_s=2.0, retries=0)
        got = r.resolve_spec("dns+big.example.org:7946")
        assert len(got) == 40, got
        assert s.tcp_queries >= 1  # served via the TCP fallback
    finally:
        s.stop()
