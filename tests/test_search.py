import os
import random

import numpy as np
import pytest

from tempo_tpu import tempopb
from tempo_tpu.backend import BlockMeta, MockBackend
from tempo_tpu.model.matches import matches
from tempo_tpu.search import (
    BackendSearchBlock,
    ColumnarPages,
    PageGeometry,
    SearchResults,
    StreamingSearchBlock,
    decode_search_data,
    encode_search_data,
    extract_search_data,
    write_search_block,
)
from tempo_tpu.search.data import SearchData, search_data_matches
from tempo_tpu.search.engine import ScanEngine, stage
from tempo_tpu.search.pipeline import compile_query, substring_value_ids
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.utils.test_data import make_trace


def _mk_req(tags=None, **kw):
    req = tempopb.SearchRequest()
    for k, v in (tags or {}).items():
        req.tags[k] = v
    for k, v in kw.items():
        setattr(req, k, v)
    return req


def _corpus(n=500, seed=0):
    rng = random.Random(seed)
    entries = []
    for i in range(n):
        tid = bytes([i % 256, i // 256]) + os.urandom(14)
        sd = SearchData(trace_id=tid.rjust(16, b"\x00")[-16:])
        sd.start_s = 1_600_000_000 + i
        sd.end_s = sd.start_s + rng.randint(0, 10)
        sd.dur_ms = rng.randint(1, 30_000)
        sd.root_service = rng.choice(["frontend", "checkout", "cart"])
        sd.root_name = "GET /"
        sd.kvs = {
            "service.name": {sd.root_service},
            "http.status_code": {str(rng.choice([200, 404, 500]))},
            "region": {rng.choice(["us-east-1", "us-west-2", "eu-west-1"])},
        }
        entries.append(sd)
    return entries


def test_search_data_codec_roundtrip():
    sd = _corpus(3)[1]
    sd2 = decode_search_data(encode_search_data(sd), sd.trace_id)
    assert sd2.start_s == sd.start_s and sd2.end_s == sd.end_s
    assert sd2.dur_ms == sd.dur_ms
    assert sd2.root_service == sd.root_service
    assert sd2.kvs == sd.kvs


def test_extract_search_data_matches_proto_oracle():
    """Extracted search data must agree with the proto-level matcher for
    tag queries (the device kernel's semantics are defined by this)."""
    for seed in range(10):
        tid = random_trace_id()
        tr = make_trace(tid, seed=seed)
        sd = extract_search_data(tid, tr)
        for req in [
            _mk_req({"component": "grpc"}),
            _mk_req({"component": "db"}),
            _mk_req({"service.name": "check"}),
            _mk_req({"http.status_code": "500"}),
            _mk_req({"nonexistent": "x"}),
        ]:
            assert search_data_matches(sd, req) == matches(tr, req), (seed, req)


def test_end_before_start_duration_clamps_to_zero():
    """ADVICE r5 medium: a span with end < start (clock skew — valid
    client input) must yield dur_ms 0 on every extraction path, not a
    negative duration that struct.error-crashes encode_search_data
    (which surfaced as HTTP 500 on push, permanently failing on retry).
    The shared convention is max(0, end - start), matching the native
    walker's clamp."""
    from tempo_tpu.modules.distributor import Distributor
    from tempo_tpu.search.data import extract_search_data
    from tempo_tpu.utils.ids import random_trace_id

    tid = random_trace_id()
    b = tempopb.ResourceSpans()
    kv = b.resource.attributes.add()
    kv.key = "service.name"
    kv.value.string_value = "skewed"
    sp = b.scope_spans.add().spans.add()
    sp.trace_id = tid
    sp.name = "op"
    sp.start_time_unix_nano = 5_000_000_000
    sp.end_time_unix_nano = 2_000_000_000  # ends "before" it starts

    trace = tempopb.Trace()
    trace.batches.append(b)
    sd = extract_search_data(tid, trace)
    assert sd.dur_ms == 0
    encode_search_data(sd)  # used to raise struct.error

    by_trace, n, sds = Distributor._regroup_extract([b], 1 << 20)
    assert n == 1
    (sd2,) = sds.values()
    assert sd2.dur_ms == 0
    encode_search_data(sd2)  # used to raise struct.error


def test_substring_value_ids():
    vd = ["alpha", "beta", "alphabet", "gamma"]
    assert substring_value_ids(vd, "alpha").tolist() == [0, 2]
    assert substring_value_ids(vd, "bet").tolist() == [1, 2]
    assert substring_value_ids(vd, "zzz").size == 0
    assert substring_value_ids(vd, "").size == 4


def test_columnar_roundtrip():
    entries = _corpus(300)
    pages = ColumnarPages.build(entries, PageGeometry(entries_per_page=64, kv_per_entry=8))
    assert pages.n_entries == 300
    assert pages.n_pages >= 300 // 64
    blob = pages.to_bytes()
    p2 = ColumnarPages.from_bytes(blob)
    assert p2.n_entries == 300
    np.testing.assert_array_equal(p2.kv_key, pages.kv_key)
    np.testing.assert_array_equal(p2.trace_ids, pages.trace_ids)
    assert p2.key_dict == pages.key_dict
    assert p2.val_dict == pages.val_dict
    assert p2.header["max_end_s"] == pages.header["max_end_s"]


QUERIES = [
    _mk_req({"service.name": "frontend"}),
    _mk_req({"service.name": "front"}),                     # substring
    _mk_req({"service.name": "frontend", "http.status_code": "500"}),
    _mk_req({"region": "us"}),                              # multi-value substring
    _mk_req({}, min_duration_ms=10_000),
    _mk_req({}, max_duration_ms=500),
    _mk_req({"service.name": "cart"}, min_duration_ms=5_000, max_duration_ms=25_000),
    _mk_req({}, start=1_600_000_100, end=1_600_000_200),
    _mk_req({"http.status_code": "404"}, start=1_600_000_050, end=1_600_000_400),
    _mk_req({"service.name": "zzz-absent"}),
]


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_engine_matches_host_oracle(qi):
    """The jit kernel must agree exactly with the host predicate."""
    req = QUERIES[qi]
    req.limit = 1000
    entries = _corpus(500)
    pages = ColumnarPages.build(entries, PageGeometry(64, 8))
    expected = {sd.trace_id for sd in entries if search_data_matches(sd, req)}

    cq = compile_query(pages.key_dict, pages.val_dict, req)
    if cq is None:
        assert not expected
        return
    eng = ScanEngine(top_k=1024)
    count, inspected, scores, idx = eng.scan(pages, cq)
    assert count == len(expected)
    assert inspected == 500
    sp = stage(pages)
    got = {bytes.fromhex(m.trace_id) for m in eng.results(sp, cq, scores, idx)}
    assert got == expected


def test_engine_topk_ordering_and_limit():
    entries = _corpus(500)
    pages = ColumnarPages.build(entries, PageGeometry(64, 8))
    req = _mk_req({"service.name": "frontend"})
    req.limit = 5
    cq = compile_query(pages.key_dict, pages.val_dict, req)
    eng = ScanEngine(top_k=128)
    sp = stage(pages)
    count, _, scores, idx = eng.scan_staged(sp, cq)
    metas = eng.results(sp, cq, scores, idx)
    assert len(metas) == 5
    starts = [m.start_time_unix_nano for m in metas]
    assert starts == sorted(starts, reverse=True)  # most recent first


def test_backend_search_block_end_to_end():
    be = MockBackend()
    meta = BlockMeta(tenant_id="t1")
    entries = _corpus(400)
    hdr = write_search_block(be, meta, entries, PageGeometry(64, 8))
    assert hdr["n_entries"] == 400

    bsb = BackendSearchBlock(be, meta)
    req = _mk_req({"service.name": "checkout"})
    req.limit = 10
    res = bsb.search(req)
    resp = res.response()
    assert 0 < len(resp.traces) <= 10
    assert resp.metrics.inspected_blocks == 1
    assert resp.metrics.inspected_traces == 400
    for m in resp.traces:
        assert m.root_service_name == "checkout"

    # pruned by dictionary prefilter: absent key never touches the device
    res2 = bsb.search(_mk_req({"absent.key": "x"}))
    assert res2.metrics.skipped_blocks == 1

    # pruned by header time range
    res3 = bsb.search(_mk_req({}, start=1_700_000_000, end=1_700_000_100))
    assert res3.metrics.skipped_blocks == 1


def test_streaming_search_block_append_scan_replay(tmp_path):
    path = str(tmp_path / "head.search")
    ssb = StreamingSearchBlock(path)
    entries = _corpus(50)
    for sd in entries:
        ssb.append(sd.trace_id, sd)
    assert len(ssb) == 50

    req = _mk_req({"service.name": "frontend"})
    req.limit = 100
    res = SearchResults(limit=100)
    ssb.search(req, res)
    expected = sum(1 for sd in entries if search_data_matches(sd, req))
    assert len(res.response().traces) == expected
    ssb.close()

    # crash replay with torn tail
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 5)
    ssb2 = StreamingSearchBlock.rescan(path)
    assert len(ssb2) == 49
    # entries() sorted by trace id, feeds columnar build
    ids = [sd.trace_id for sd in ssb2.entries()]
    assert ids == sorted(ids)
    ssb2.clear()
    assert not os.path.exists(path)


def test_results_dedupe_and_sort():
    res = SearchResults(limit=10)
    m1 = tempopb.TraceSearchMetadata(trace_id="aa", start_time_unix_nano=5, duration_ms=10)
    m2 = tempopb.TraceSearchMetadata(trace_id="aa", start_time_unix_nano=3, duration_ms=20)
    m3 = tempopb.TraceSearchMetadata(trace_id="bb", start_time_unix_nano=9)
    for m in (m1, m2, m3):
        res.add(m)
    resp = res.response()
    assert len(resp.traces) == 2
    assert resp.traces[0].trace_id == "bb"  # most recent first
    aa = resp.traces[1]
    assert aa.start_time_unix_nano == 3 and aa.duration_ms == 20


def test_engine_limit_above_default_topk():
    """Requesting more results than the engine's default top_k must not
    silently truncate (regression: results were capped at top_k=128)."""
    entries = _corpus(500)  # ~1/3 match frontend
    pages = ColumnarPages.build(entries, PageGeometry(64, 8))
    req = _mk_req({"service.name": "frontend"})
    req.limit = 400
    cq = compile_query(pages.key_dict, pages.val_dict, req)
    eng = ScanEngine(top_k=16)  # deliberately tiny default
    sp = stage(pages)
    count, _, scores, idx = eng.scan_staged(sp, cq)
    metas = eng.results(sp, cq, scores, idx)
    assert len(metas) == count  # every match surfaced, not 16


def test_columnar_adaptive_kv_capacity():
    """Build sizes C to the widest entry (pow2), capped by geometry;
    regression: a fixed small C silently dropped searchable tags."""
    wide = SearchData(trace_id=b"\x01" * 16, start_s=1, end_s=2, dur_ms=5)
    wide.kvs = {f"k{i}": {f"v{i}"} for i in range(11)}
    pages = ColumnarPages.build([wide], PageGeometry(entries_per_page=4))
    assert pages.geometry.kv_per_entry == 16  # next pow2 of 11
    assert pages.header["truncated_entries"] == 0
    req = _mk_req({"k10": "v10"})
    cq = compile_query(pages.key_dict, pages.val_dict, req)
    count, _, _, _ = ScanEngine().scan(pages, cq)
    assert count == 1
    # cap still enforced
    pages2 = ColumnarPages.build([wide], PageGeometry(4, 8))
    assert pages2.geometry.kv_per_entry == 8
    assert pages2.header["truncated_entries"] == 1


def test_native_substr_scan_matches_numpy():
    from tempo_tpu.ops import native
    from tempo_tpu.search.pipeline import pack_val_dict
    if not native.available():
        pytest.skip("native lib unavailable")
    vd = sorted({f"val-{i:06d}-{'x' if i % 3 else 'special'}" for i in range(10_000)})
    buf, offsets = pack_val_dict(vd)
    for needle in ("special", "val-0001", "zzz", "", "-x"):
        got = native.substr_scan(buf, offsets, needle.encode()).tolist()
        arr = np.array(vd, dtype=np.str_)
        want = np.nonzero(np.char.find(arr, needle) >= 0)[0].tolist()
        assert got == want, needle


def test_multiblock_scan_matches_per_block():
    from tempo_tpu.search.multiblock import (
        MultiBlockEngine, compile_multi, stack_blocks,
    )

    corpora = [_corpus(120, seed=s) for s in range(4)]
    blocks = [
        ColumnarPages.build(entries, PageGeometry(32, 8))
        for entries in corpora
    ]
    req = _mk_req({"service.name": "frontend"})
    req.limit = 1000
    mq = compile_multi(blocks, req)
    assert mq is not None
    batch = stack_blocks(blocks, pad_to=32)
    eng = MultiBlockEngine(top_k=1024)
    count, inspected, scores, idx = eng.scan(batch, mq)

    expected = {
        sd.trace_id
        for entries in corpora for sd in entries
        if search_data_matches(sd, req)
    }
    assert inspected == 480
    assert count == len(expected)
    got = {bytes.fromhex(m.trace_id) for m in eng.results(batch, mq, scores, idx)}
    assert got == expected


def test_multiblock_per_block_dictionaries_differ():
    """The same tag value gets DIFFERENT ids in different blocks — the
    per-page term tables must still resolve correctly."""
    from tempo_tpu.search.multiblock import (
        MultiBlockEngine, compile_multi, stack_blocks,
    )

    a = SearchData(trace_id=b"\x01" * 16, start_s=10, end_s=20, dur_ms=5)
    a.kvs = {"k": {"target"}, "zz": {"aaaa"}}
    b = SearchData(trace_id=b"\x02" * 16, start_s=10, end_s=20, dur_ms=5)
    b.kvs = {"k": {"target"}, "aa": {"zzzz"}}  # shifts dictionary ids
    c = SearchData(trace_id=b"\x03" * 16, start_s=10, end_s=20, dur_ms=5)
    c.kvs = {"k": {"other"}}
    blocks = [ColumnarPages.build([a], PageGeometry(4, 8)),
              ColumnarPages.build([b, c], PageGeometry(4, 8))]
    req = _mk_req({"k": "target"})
    req.limit = 10
    mq = compile_multi(blocks, req)
    batch = stack_blocks(blocks)
    eng = MultiBlockEngine()
    count, _, scores, idx = eng.scan(batch, mq)
    assert count == 2
    got = {m.trace_id for m in eng.results(batch, mq, scores, idx)}
    assert got == {(b"\x01" * 16).hex(), (b"\x02" * 16).hex()}


def test_stack_host_narrows_kv_dtypes():
    """VERDICT r4 #2: small dictionaries stack as int8/int16 so HBM
    bytes and the evicted-group re-stage shrink; results stay identical
    to the int32 path (the kernel promotes inline)."""
    import numpy as np

    from tempo_tpu.search.multiblock import (
        MultiBlockEngine, compile_multi, stack_blocks, stack_host,
    )

    blocks = [ColumnarPages.build(_corpus(40, seed=s), PageGeometry(8, 8))
              for s in range(3)]
    host = stack_host(blocks)
    assert host.cat["kv_key"].dtype == np.int8
    assert host.cat["kv_val"].dtype in (np.int8, np.int16)
    # padded slots keep the -1 sentinel through the cast
    assert (host.cat["kv_key"] >= -1).all()

    # NB: not the ("service.name", "front") pair — the global compile
    # cache is keyed by (dict fingerprint, tag-sig) and
    # test_compile_cache_skips_dictionary_probe asserts that pair cold
    req = _mk_req({"service.name": "ront"})
    req.limit = 1000
    mq = compile_multi(blocks, req)
    eng = MultiBlockEngine()
    count, inspected, scores, idx = eng.scan(stack_blocks(blocks), mq)
    expected = sum(
        1 for s in range(3) for sd in _corpus(40, seed=s)
        if any("ront" in v for v in sd.kvs.get("service.name", ())))
    assert int(count) == expected


def test_stack_host_wide_dicts_stay_int32():
    import numpy as np

    from tempo_tpu.search.multiblock import stack_host

    b = ColumnarPages.build(_corpus(20), PageGeometry(8, 8))
    b.val_dict = b.val_dict + [f"v{i:07d}" for i in range(40_000)]
    host = stack_host([b])
    assert host.cat["kv_val"].dtype == np.int32


def test_compile_multi_skipped_group_wider_ranges():
    """code-review r5: a dict group whose EVERY row is header-skipped may
    compile more disjoint value-id ranges than the unskipped width —
    assembly must clamp both axes, and the skipped rows end masked."""
    from tempo_tpu.search.multiblock import compile_multi

    a = SearchData(trace_id=b"\x01" * 16, start_s=10, end_s=20, dur_ms=5)
    a.kvs = {"k": {"svcA"}}
    # disjoint dictionary ids for the substring "svc" → R_cq = 2 ranges
    b = SearchData(trace_id=b"\x02" * 16, start_s=10, end_s=20, dur_ms=5)
    b.kvs = {"k": {"asvcq"}, "m": {"bbb"}, "n": {"csvcq"}}
    blocks = [ColumnarPages.build([a], PageGeometry(4, 8)),
              ColumnarPages.build([b], PageGeometry(4, 8))]
    req = _mk_req({"k": "svc"})
    req.limit = 10
    mq = compile_multi(blocks, req, skip=[False, True])
    assert mq is not None
    assert (mq.term_keys[1] == -1).all()          # skipped row masked
    assert (mq.val_ranges[1, :, :, 0] == 1).all()  # empty [1,0] ranges
    assert (mq.val_ranges[1, :, :, 1] == 0).all()
    assert (mq.term_keys[0] != -1).any()           # live row intact


def test_compile_cache_skips_dictionary_probe():
    """Per-(block, tag-set) compile cache (VERDICT r2 #1): the second
    compilation of the same tags against the same block skips the
    dictionary probe entirely; different scalars (window/duration/limit)
    reuse the cached probe; different tags or the prune result are
    cached separately."""
    from unittest import mock

    from tempo_tpu.search import pipeline
    from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
    pages = ColumnarPages.build(_corpus(50), PageGeometry(16, 8))
    req = _mk_req({"service.name": "front"})
    req.limit = 5

    with mock.patch.object(pipeline, "substring_value_ids",
                           wraps=pipeline.substring_value_ids) as probe:
        cq1 = pipeline.compile_query(pages.key_dict, pages.val_dict, req,
                                     cache_on=pages)
        n_cold = probe.call_count
        assert n_cold >= 1
        # same tags, different scalars -> cache hit, fresh scalars
        req2 = _mk_req({"service.name": "front"})
        req2.limit = 99
        req2.min_duration_ms = 123
        cq2 = pipeline.compile_query(pages.key_dict, pages.val_dict, req2,
                                     cache_on=pages)
        assert probe.call_count == n_cold  # no new probes
        assert cq2.limit == 99 and cq2.dur_lo == 123
        assert (cq1.term_keys == cq2.term_keys).all()
        assert (cq1.val_ranges == cq2.val_ranges).all()

        # pruned result cached too
        miss = _mk_req({"no.such.key": "x"})
        assert pipeline.compile_query(pages.key_dict, pages.val_dict, miss,
                                      cache_on=pages) is None
        n_after_miss = probe.call_count
        assert pipeline.compile_query(pages.key_dict, pages.val_dict, miss,
                                      cache_on=pages) is None
        assert probe.call_count == n_after_miss

    # uncached path still works (no cache_on)
    cq3 = pipeline.compile_query(pages.key_dict, pages.val_dict, req)
    assert (cq3.term_keys == cq1.term_keys).all()


def test_engine_randomized_differential_vs_oracle():
    """Property fuzz: random corpora × random predicates × random page
    geometry must agree EXACTLY with the host oracle — fixed query lists
    miss edge interactions (empty windows, dur bounds at the sample
    values, substring terms matching zero/all dictionary entries)."""
    rng = random.Random(1234)
    for round_ in range(25):
        entries = _corpus(n=rng.randint(1, 300), seed=rng.randint(0, 10**6))
        E = rng.choice([8, 64, 256])
        C = rng.choice([4, 8, 16])
        pages = ColumnarPages.build(entries, PageGeometry(E, C))

        tags = {}
        for _ in range(rng.randint(0, 3)):
            k = rng.choice(["service.name", "http.status_code", "region",
                            "component", "nope.key"])
            v = rng.choice(["front", "frontend", "cart", "5", "500", "us",
                            "db", "zz-none", ""])
            if v:
                tags[k] = v
        kw = {}
        if rng.random() < 0.5:
            kw["min_duration_ms"] = rng.choice([1, 500, 5_000, 30_000])
        if rng.random() < 0.5:
            kw["max_duration_ms"] = rng.choice([100, 5_000, 60_000])
        if rng.random() < 0.5:
            kw["start"] = 1_600_000_000 + rng.randint(-50, 400)
            kw["end"] = kw["start"] + rng.randint(0, 300)
        req = _mk_req(tags, **kw)
        req.limit = 1000

        expected = {sd.trace_id for sd in entries
                    if search_data_matches(sd, req)}
        cq = compile_query(pages.key_dict, pages.val_dict, req)
        if cq is None:
            assert not expected, (round_, tags, kw)
            continue
        eng = ScanEngine(top_k=1024)
        count, inspected, scores, idx = eng.scan(pages, cq)
        assert count == len(expected), (round_, tags, kw)
        assert inspected == len(entries)
        sp = stage(pages)
        got = {bytes.fromhex(m.trace_id)
               for m in eng.results(sp, cq, scores, idx)}
        assert got == expected, (round_, tags, kw)
