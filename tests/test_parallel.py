import jax
import numpy as np
import pytest

from tempo_tpu import tempopb
from tempo_tpu.parallel import DistributedScanEngine, make_mesh
from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
from tempo_tpu.search.data import search_data_matches
from tempo_tpu.search.engine import ScanEngine
from tempo_tpu.search.pipeline import compile_query

from tests.test_search import _corpus, _mk_req, QUERIES


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    mesh = make_mesh()
    assert mesh.devices.size == 8


@pytest.mark.parametrize("qi", [0, 2, 4, 7])
def test_distributed_scan_matches_single_device(qi):
    req = QUERIES[qi]
    req.limit = 1000
    entries = _corpus(500)
    pages = ColumnarPages.build(entries, PageGeometry(32, 8))
    cq = compile_query(pages.key_dict, pages.val_dict, req)
    if cq is None:
        pytest.skip("query prunes block")

    single = ScanEngine(top_k=1024)
    s_count, s_inspected, _, _ = single.scan(pages, cq)

    mesh = make_mesh()
    dist = DistributedScanEngine(mesh, top_k=1024)
    sp = dist.stage(pages)
    d_count, d_inspected, scores, idx = dist.scan_staged(sp, cq)

    assert d_count == s_count
    assert d_inspected == s_inspected

    expected = {sd.trace_id for sd in entries if search_data_matches(sd, req)}
    got = {bytes.fromhex(m.trace_id) for m in dist.results(sp, cq, scores, idx)}
    assert got == expected


def test_distributed_stage_shards_pages():
    entries = _corpus(300)
    pages = ColumnarPages.build(entries, PageGeometry(32, 8))
    mesh = make_mesh()
    dist = DistributedScanEngine(mesh)
    sp = dist.stage(pages)
    arr = sp.device["kv_key"]
    assert arr.shape[0] % 8 == 0
    # each of the 8 devices holds a distinct contiguous page shard
    assert len(arr.sharding.device_set) == 8


# ---------------------------------------------------------------------------
# round 2: the distributed MULTI-BLOCK kernel (the serving path on a mesh)


def _blocks(n_blocks, per_block, geometry=PageGeometry(32, 8)):
    all_entries, blocks = [], []
    for b in range(n_blocks):
        entries = _corpus(per_block, seed=b * 7 + 1)
        all_entries.append(entries)
        blocks.append(ColumnarPages.build(entries, geometry))
    return all_entries, blocks


@pytest.mark.parametrize("qi", [0, 2, 4, 7])
def test_dist_multiblock_matches_single_device(qi):
    """Mesh-sharded batched scan == single-device batched scan == host
    oracle, including result identity (not just counts)."""
    from tempo_tpu.search.multiblock import MultiBlockEngine, compile_multi

    req = QUERIES[qi]
    req.limit = 2000
    all_entries, blocks = _blocks(5, 120)
    mq = compile_multi(blocks, req)
    if mq is None:
        pytest.skip("query prunes every block")

    single = MultiBlockEngine(top_k=1024)
    sb = single.stage(blocks)
    s_count, s_inspected, s_scores, s_idx = single.scan(sb, mq)

    dist = MultiBlockEngine(top_k=1024, mesh=make_mesh())
    db_ = dist.stage(blocks)
    d_count, d_inspected, d_scores, d_idx = dist.scan(db_, mq)

    assert d_count == s_count and d_inspected == s_inspected

    expected = {sd.trace_id for entries in all_entries for sd in entries
                if search_data_matches(sd, req)}
    got_single = {bytes.fromhex(m.trace_id)
                  for m in single.results(sb, mq, s_scores, s_idx)}
    got_dist = {bytes.fromhex(m.trace_id)
                for m in dist.results(db_, mq, d_scores, d_idx)}
    assert got_single == expected
    assert got_dist == expected


def test_dist_multiblock_uneven_pages_and_padding():
    """Blocks with uneven page counts (total not divisible by the shard
    count) pad with invalid pages; counts must ignore the padding."""
    from tempo_tpu.search.multiblock import MultiBlockEngine, compile_multi

    # 3 blocks x different sizes -> 3+1+2=6 pages, padded to 8 over mesh
    geometry = PageGeometry(32, 8)
    e1 = _corpus(90, seed=1)   # 3 pages
    e2 = _corpus(20, seed=2)   # 1 page
    e3 = _corpus(64, seed=3)   # 2 pages
    blocks = [ColumnarPages.build(e, geometry) for e in (e1, e2, e3)]
    req = _mk_req({})
    req.limit = 500
    mq = compile_multi(blocks, req)
    dist = MultiBlockEngine(top_k=512, mesh=make_mesh())
    batch = dist.stage(blocks)
    assert batch.device["kv_key"].shape[0] % 8 == 0
    count, inspected, scores, idx = dist.scan(batch, mq)
    assert inspected == 90 + 20 + 64
    assert count == sum(
        1 for e in (e1, e2, e3) for sd in e if search_data_matches(sd, req))


def test_dist_multiblock_pruned_block_in_batch():
    """A block whose dictionary prunes the query stays in the batch but
    contributes no matches on any shard."""
    from tempo_tpu.search.multiblock import MultiBlockEngine, compile_multi

    geometry = PageGeometry(32, 8)
    hit = _corpus(64, seed=1)
    miss = []
    for sd in _corpus(64, seed=2):
        sd.kvs = {"other.key": {"zzz"}}
        miss.append(sd)
    blocks = [ColumnarPages.build(hit, geometry),
              ColumnarPages.build(miss, geometry)]
    req = _mk_req({"service.name": "frontend"})
    req.limit = 500
    mq = compile_multi(blocks, req)
    assert mq is not None
    assert int(mq.term_keys[1, 0]) == -1  # second block pruned
    dist = MultiBlockEngine(top_k=512, mesh=make_mesh())
    count, _, scores, idx = dist.scan(dist.stage(blocks), mq)
    expected = {sd.trace_id for sd in hit
                if search_data_matches(sd, req)}
    assert count == len(expected)


def test_dist_multiblock_limit_exceeds_topk():
    """limit > engine top_k: top_k doubles until it covers the limit on
    the mesh path too (scores come back globally merged)."""
    from tempo_tpu.search.multiblock import MultiBlockEngine, compile_multi

    _, blocks = _blocks(4, 100)
    req = _mk_req({})
    req.limit = 300  # > top_k=64
    mq = compile_multi(blocks, req)
    dist = MultiBlockEngine(top_k=64, mesh=make_mesh())
    count, _, scores, idx = dist.scan(dist.stage(blocks), mq)
    assert count == 400
    assert scores.shape[0] >= 300  # top_k grew to cover the limit
    # indices must be unique, valid, and in score order
    assert len(set(idx.tolist())) == idx.shape[0]
    assert all(scores[i] >= scores[i + 1] for i in range(len(scores) - 1))


def test_tempodb_search_on_mesh_equals_no_mesh(tmp_path):
    """The SERVING entry on a mesh: TempoDB.search with auto-meshed
    devices returns byte-identical results to the single-device path."""
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig

    per_block = [_corpus(50, seed=b) for b in range(4)]

    def build(path, mesh):
        be = LocalBackend(str(path / "blocks"))
        db = TempoDB(be, str(path / "wal"),
                     TempoDBConfig(auto_mesh=False), mesh=mesh)
        for entries in per_block:
            db.write_block_direct(
                "t1",
                sorted((sd.trace_id, b"\x01", sd.start_s, sd.end_s)
                       for sd in entries),
                search_entries=entries)
        return db

    req = _mk_req({"service.name": "frontend"})
    req.limit = 500

    p1 = tmp_path / "nomesh"
    p1.mkdir()
    r1 = build(p1, None).search("t1", req).response()
    p2 = tmp_path / "mesh"
    p2.mkdir()
    db2 = build(p2, make_mesh())
    assert db2.batcher.engine.mesh is not None
    r2 = db2.search("t1", req).response()

    ids1 = sorted(t.trace_id for t in r1.traces)
    ids2 = sorted(t.trace_id for t in r2.traces)
    assert ids1 == ids2 and len(ids1) > 0
    assert r1.metrics.inspected_traces == r2.metrics.inspected_traces
