import jax
import numpy as np
import pytest

from tempo_tpu import tempopb
from tempo_tpu.parallel import DistributedScanEngine, make_mesh
from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
from tempo_tpu.search.data import search_data_matches
from tempo_tpu.search.engine import ScanEngine
from tempo_tpu.search.pipeline import compile_query

from tests.test_search import _corpus, _mk_req, QUERIES


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    mesh = make_mesh()
    assert mesh.devices.size == 8


@pytest.mark.parametrize("qi", [0, 2, 4, 7])
def test_distributed_scan_matches_single_device(qi):
    req = QUERIES[qi]
    req.limit = 1000
    entries = _corpus(500)
    pages = ColumnarPages.build(entries, PageGeometry(32, 8))
    cq = compile_query(pages.key_dict, pages.val_dict, req)
    if cq is None:
        pytest.skip("query prunes block")

    single = ScanEngine(top_k=1024)
    s_count, s_inspected, _, _ = single.scan(pages, cq)

    mesh = make_mesh()
    dist = DistributedScanEngine(mesh, top_k=1024)
    sp = dist.stage(pages)
    d_count, d_inspected, scores, idx = dist.scan_staged(sp, cq)

    assert d_count == s_count
    assert d_inspected == s_inspected

    expected = {sd.trace_id for sd in entries if search_data_matches(sd, req)}
    got = {bytes.fromhex(m.trace_id) for m in dist.results(sp, cq, scores, idx)}
    assert got == expected


def test_distributed_stage_shards_pages():
    entries = _corpus(300)
    pages = ColumnarPages.build(entries, PageGeometry(32, 8))
    mesh = make_mesh()
    dist = DistributedScanEngine(mesh)
    sp = dist.stage(pages)
    arr = sp.device["kv_key"]
    assert arr.shape[0] % 8 == 0
    # each of the 8 devices holds a distinct contiguous page shard
    assert len(arr.sharding.device_set) == 8
