import os
import time

import pytest

from tempo_tpu import tempopb
from tempo_tpu.modules import App, AppConfig, Overrides, Limits, Ring
from tempo_tpu.modules.distributor import RateLimited, IngestError
from tempo_tpu.modules.frontend import create_block_boundaries
from tempo_tpu.modules.ingester import LimitError
from tempo_tpu.db import TempoDBConfig
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.utils.test_data import make_trace

from tests.test_search import _mk_req


def _app(tmp_path, **kw):
    cfg = AppConfig(wal_dir=str(tmp_path / "wal"), **kw)
    return App(cfg)


def _push_traces(app, tenant, n, seed_base=0):
    traces = {}
    for i in range(n):
        tid = random_trace_id()
        tr = make_trace(tid, seed=seed_base + i)
        app.push(tenant, list(tr.batches))
        traces[tid] = tr
    return traces


# ---- ring ----

def test_ring_replication_and_health():
    ring = Ring(replication_factor=2)
    for i in range(3):
        ring.register(f"i{i}")
    got = ring.get(12345)
    assert len(got) == 2 and len(set(got)) == 2
    # same token → same placement
    assert ring.get(12345) == got
    # leaving shifts placement to the remaining healthy instances
    ring.leave(got[0])
    got2 = ring.get(12345)
    assert got[0] not in got2 and len(got2) == 2


def test_ring_owns_exactly_one():
    ring = Ring()
    for i in range(4):
        ring.register(f"i{i}")
    for token in (0, 123, 2**31, 2**32 - 1):
        owners = [i for i in ring.instance_ids() if ring.owns(i, token)]
        assert len(owners) == 1


# ---- overrides ----

def test_overrides_limits_and_reload():
    ov = Overrides(Limits(max_live_traces=5), {"vip": {"max_live_traces": 100}})
    assert ov.limits("any").max_live_traces == 5
    assert ov.limits("vip").max_live_traces == 100
    ov.reload({"any": {"max_live_traces": 7}})
    assert ov.limits("any").max_live_traces == 7
    assert ov.limits("vip").max_live_traces == 5


def test_overrides_rate_limit():
    ov = Overrides(Limits(ingestion_rate_bytes=100, ingestion_burst_bytes=100))
    assert ov.allow_ingestion("t", 80)
    assert not ov.allow_ingestion("t", 80)  # burst exhausted


# ---- write path e2e ----

def test_push_cut_complete_find(tmp_path):
    app = _app(tmp_path)
    traces = _push_traces(app, "t1", 20)

    # live lookup via frontend (ingester leg)
    tid, tr = next(iter(traces.items()))
    resp = app.find_trace(tid=tid, tenant="t1") if False else app.find_trace("t1", tid)
    assert len(resp.trace.batches) == len(tr.batches)

    # flush everything to the backend, then read the block leg
    completed = app.flush_tick(force=True)
    assert len(completed) == 1
    app.poll_tick()
    resp = app.find_trace("t1", tid)
    assert len(resp.trace.batches) == len(tr.batches)


def test_search_live_and_backend(tmp_path):
    app = _app(tmp_path)
    _push_traces(app, "t1", 30)

    req = _mk_req({})
    req.limit = 100
    # live (ingester) search before any flush
    resp = app.search("t1", req)
    assert len(resp.traces) == 30

    app.flush_tick(force=True)
    app.poll_tick()
    resp = app.search("t1", req)
    assert len(resp.traces) == 30

    # tag search against specific content
    req2 = _mk_req({"component": "db"})
    req2.limit = 100
    resp2 = app.search("t1", req2)
    assert 0 < len(resp2.traces) <= 30


def test_replication_factor_2_survives_one_down(tmp_path):
    app = _app(tmp_path, n_ingesters=3, replication_factor=2)
    traces = _push_traces(app, "t1", 10)

    # kill one ingester entirely: reads still find every trace
    dead = next(iter(app.ingesters))
    app.queriers[0].ingesters = dict(app.ingesters)
    del app.queriers[0].ingesters[dead]
    for tid in traces:
        resp = app.queriers[0].find_trace_by_id("t1", tid)
        assert len(resp.trace.batches) > 0, "trace lost with one replica down"


def test_ingester_replay_after_crash(tmp_path):
    app = _app(tmp_path)
    traces = _push_traces(app, "t1", 15)
    # cut live traces into the WAL head block but do NOT complete
    for ing in app.ingesters.values():
        ing.instance("t1").cut_complete_traces(force=True)

    # "crash": rebuild the app over the same wal dir + backend
    from tempo_tpu.modules.ingester import Ingester

    ing2 = Ingester(app.ingesters["ingester-0"].db, app.overrides,
                    instance_id="ingester-0")
    assert ing2.replayed_blocks >= 1
    completed = ing2.sweep(force=True)
    assert completed and completed[0].total_objects == 15

    app.poll_tick()
    tid = next(iter(traces))
    obj, _ = app.reader_db.find_trace_by_id("t1", tid)
    assert obj is not None

    # search WAL replayed too: search the completed block
    req = _mk_req({})
    req.limit = 100
    res = app.reader_db.search("t1", req)
    assert len(res.response().traces) == 15


def test_limits_enforced(tmp_path):
    app = _app(tmp_path)
    app.overrides.reload({"t1": {"max_live_traces": 3}})
    # the replica's LimitError surfaces through the distributor's quorum
    # check as an IngestError (the client-facing failure)
    with pytest.raises((LimitError, IngestError)):
        _push_traces(app, "t1", 10)

    app2 = _app(tmp_path / "b")
    app2.overrides.reload({"t1": {"ingestion_rate_bytes": 10,
                                  "ingestion_burst_bytes": 10}})
    with pytest.raises(RateLimited):
        _push_traces(app2, "t1", 5)


def test_multitenancy_isolated(tmp_path):
    app = _app(tmp_path)
    t1 = _push_traces(app, "t1", 5)
    t2 = _push_traces(app, "t2", 5)
    app.flush_tick(force=True)
    app.poll_tick()
    # t1 ids are not visible under t2
    tid = next(iter(t1))
    assert len(app.find_trace("t2", tid).trace.batches) == 0
    assert len(app.find_trace("t1", tid).trace.batches) > 0
    req = _mk_req({})
    req.limit = 100
    assert len(app.search("t2", req).traces) == 5


def test_block_boundaries_cover_space():
    bounds = create_block_boundaries(4)
    assert len(bounds) == 5
    assert bounds[0] == "00000000-0000-0000-0000-000000000000"
    assert bounds[-1] == "ffffffff-ffff-ffff-ffff-ffffffffffff"
    assert bounds == sorted(bounds)


def test_full_lifecycle_with_compaction(tmp_path):
    """ingest → flush → poll → compact → search + find still correct."""
    # fabricated traces sit at a 2020 epoch — disable retention so the
    # compacted output isn't immediately aged out
    app = _app(tmp_path, db=TempoDBConfig(compaction_window_s=10**10,
                                          retention_s=10**10))
    all_traces = {}
    for round_ in range(3):
        all_traces.update(_push_traces(app, "t1", 10, seed_base=round_ * 100))
        app.flush_tick(force=True)
    app.poll_tick()
    assert len(app.reader_db.blocklist.metas("t1")) == 3

    app.compaction_tick()
    live = app.reader_db.blocklist.metas("t1")
    assert len(live) == 1 and live[0].compaction_level == 1

    req = _mk_req({})
    req.limit = 100
    assert len(app.search("t1", req).traces) == 30
    tid = next(iter(all_traces))
    assert len(app.find_trace("t1", tid).trace.batches) > 0

    # shutdown flushes cleanly
    app.shutdown()


def test_ready_and_shutdown(tmp_path):
    app = _app(tmp_path)
    assert app.ready()
    _push_traces(app, "t1", 3)
    app.shutdown()
    app.poll_tick()
    req = _mk_req({})
    req.limit = 10
    res = app.reader_db.search("t1", req)
    assert len(res.response().traces) == 3


def test_find_during_blocklist_poll_gap(tmp_path):
    """After a block completes but BEFORE the reader polls, traces must
    stay queryable via the ingester's recently-completed window
    (regression: complete_one dropped visibility until the next poll)."""
    app = _app(tmp_path)
    traces = _push_traces(app, "t1", 8)
    completed = app.flush_tick(force=True)
    assert completed
    # NOTE: no app.poll_tick() — reader blocklist is empty
    assert app.reader_db.blocklist.metas("t1") == []
    tid = next(iter(traces))
    resp = app.find_trace("t1", tid)
    assert len(resp.trace.batches) > 0
    req = _mk_req({})
    req.limit = 20
    assert len(app.search("t1", req).traces) == 8


def test_complete_one_restores_on_failure(tmp_path):
    """A failed backend write must not lose the completing block."""
    app = _app(tmp_path)
    _push_traces(app, "t1", 5)
    ing = app.ingesters["ingester-0"]
    inst = ing.instance("t1")
    inst.cut_complete_traces(force=True)
    inst.cut_block_if_ready(force=True)
    assert len(inst.completing) == 1

    real_write = app.backend.write
    app.backend.write = lambda *a, **k: (_ for _ in ()).throw(OSError("flake"))
    with pytest.raises(OSError):
        inst.complete_one()
    assert len(inst.completing) == 1  # restored, not lost
    app.backend.write = real_write
    inst.completing[0].retry_at = 0.0  # elapse the flush backoff window
    assert inst.complete_one() is not None  # retried successfully


# ---- round 2: page-range job sharding + batched dispatch + early quit ----

def _frontend_db(tmp_path, n_blocks=3, per_block=200, **db_kw):
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.model.codec import codec_for
    from tempo_tpu.search.columnar import PageGeometry
    from tempo_tpu.search.data import extract_search_data

    db = TempoDB(LocalBackend(str(tmp_path / "blocks")), str(tmp_path / "w"),
                 TempoDBConfig(search_geometry=PageGeometry(32, 16), **db_kw))
    codec = codec_for("v2")
    all_sds = []
    for b in range(n_blocks):
        objs, sds = [], []
        for i in range(per_block):
            tid = random_trace_id()
            tr = make_trace(tid, seed=b * 1000 + i)
            sd = extract_search_data(tid, tr)
            objs.append((tid, codec.marshal(tr, sd.start_s, sd.end_s),
                         sd.start_s, sd.end_s))
            sds.append(sd)
        db.write_block_direct("t1", sorted(objs), search_entries=sds)
        all_sds.extend(sds)
    return db, all_sds


def test_frontend_page_range_jobs_merge_to_whole(tmp_path):
    """A large block splits into N page-range jobs whose merged result
    equals the single-job result (reference searchsharding.go:323-367),
    and the job encoding comes from the block meta, not a constant."""
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier
    from tempo_tpu.search.data import search_data_matches

    db, all_sds = _frontend_db(tmp_path)
    metas = db.blocklist.metas("t1")
    assert all(m.search_pages > 1 for m in metas)  # multi-page containers

    q = Querier(db, Ring(), {})
    # tiny job target -> one page per job
    fe_split = QueryFrontend([q], FrontendConfig(target_bytes_per_job=1,
                                                 batch_jobs_per_request=4))
    jobs = fe_split._block_jobs(metas)
    assert len(jobs) == sum(m.search_pages for m in metas)
    assert {j[0].encoding for j in jobs} == {m.encoding for m in metas}

    # huge target -> one job per block
    fe_whole = QueryFrontend([q], FrontendConfig())
    assert len(fe_whole._block_jobs(metas)) == len(metas)

    req = _mk_req({"component": "grpc"})
    req.limit = 10_000
    r_split = fe_split.search("t1", req)
    r_whole = fe_whole.search("t1", req)
    expected = {sd.trace_id.hex() for sd in all_sds
                if search_data_matches(sd, req)}
    assert {t.trace_id for t in r_split.traces} == expected
    assert {t.trace_id for t in r_whole.traces} == expected
    assert r_split.metrics.inspected_traces == r_whole.metrics.inspected_traces


def test_frontend_mixed_encoding_blocks(tmp_path):
    """Blocks written with different codecs search correctly through the
    page-range path (round-1 hardcoded 'zstd' would corrupt this)."""
    from tempo_tpu.encoding.v2.compression import encoding_usable
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier
    from tempo_tpu.search.data import search_data_matches

    if not (encoding_usable("lz4") and encoding_usable("snappy")):
        pytest.skip("mixed-codec test needs the native lib")

    db, sds1 = _frontend_db(tmp_path, n_blocks=1)
    db.cfg.block_encoding = "lz4"
    db.cfg.search_encoding = "snappy"
    from tempo_tpu.model.codec import codec_for
    from tempo_tpu.search.data import extract_search_data
    codec = codec_for("v2")
    objs, sds2 = [], []
    for i in range(150):
        tid = random_trace_id()
        tr = make_trace(tid, seed=5000 + i)
        sd = extract_search_data(tid, tr)
        objs.append((tid, codec.marshal(tr, sd.start_s, sd.end_s),
                     sd.start_s, sd.end_s))
        sds2.append(sd)
    db.write_block_direct("t1", sorted(objs), search_entries=sds2)

    metas = db.blocklist.metas("t1")
    assert {m.encoding for m in metas} == {"zstd", "lz4"}

    q = Querier(db, Ring(), {})
    fe = QueryFrontend([q], FrontendConfig(target_bytes_per_job=1))
    req = _mk_req({"component": "grpc"})
    req.limit = 10_000
    r = fe.search("t1", req)
    expected = {sd.trace_id.hex() for sd in sds1 + sds2
                if search_data_matches(sd, req)}
    assert {t.trace_id for t in r.traces} == expected


def test_frontend_early_quit_stops_dispatch(tmp_path):
    """A limit-hit query over many batches cancels the remaining jobs:
    inspected_blocks << total (reference results.go:38-78 quit +
    searchsharding.go stop-dispatch)."""
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier

    db, all_sds = _frontend_db(tmp_path, n_blocks=8, per_block=64)
    q = Querier(db, Ring(), {})
    fe = QueryFrontend([q], FrontendConfig(batch_jobs_per_request=1,
                                           max_concurrent_jobs=1))
    req = _mk_req({})
    req.limit = 5
    r = fe.search("t1", req)
    assert len(r.traces) == 5
    assert r.metrics.inspected_blocks < 8, r.metrics


def test_frontend_tolerance_counts_blocks_not_batches(tmp_path):
    """One failed SearchBlocksRequest covers all its blocks: tolerance
    compares BLOCK counts (reference tolerate_failed_blocks semantics),
    not batch counts."""
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier

    db, _ = _frontend_db(tmp_path, n_blocks=4, per_block=40)
    q = Querier(db, Ring(), {})

    class FailingBatches:
        """Querier facade that fails every batched block request."""
        def search_recent(self, tenant, req):
            return q.search_recent(tenant, req)

        def search_blocks(self, breq):
            raise RuntimeError("querier down")

    req = _mk_req({})
    req.limit = 10_000

    # tolerance 3 < 4 failed blocks (one batch of 4) -> error surfaces
    fe = QueryFrontend([FailingBatches()], FrontendConfig(
        batch_jobs_per_request=4, retries=0, tolerate_failed_blocks=3), db=db)
    with pytest.raises(RuntimeError):
        fe.search("t1", req)

    # tolerance 4 covers it -> partial (ingester-only) result, FAILED=4
    # (failed stays failed — pruning skips, breakage fails)
    fe2 = QueryFrontend([FailingBatches()], FrontendConfig(
        batch_jobs_per_request=4, retries=0, tolerate_failed_blocks=4), db=db)
    r = fe2.search("t1", req)
    assert r.metrics.failed_blocks == 4
    assert r.metrics.skipped_blocks == 0


def test_frontend_failed_block_spanning_batches_counts_once(tmp_path):
    """A block whose page-range jobs land in SEVERAL failed batches is one
    failed block, not one per batch (ADVICE r2 item 2: shared id set)."""
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier

    db, _ = _frontend_db(tmp_path, n_blocks=1, per_block=120)
    (meta,) = db.blocklist.metas("t1")
    assert meta.search_pages >= 3  # jobs will span >1 batch
    q = Querier(db, Ring(), {})

    class FailingBatches:
        def search_recent(self, tenant, req):
            return q.search_recent(tenant, req)

        def search_blocks(self, breq):
            raise RuntimeError("querier down")

    req = _mk_req({})
    req.limit = 10_000
    # one page per job, one job per batch -> the single block spans
    # search_pages failed batches; tolerance 1 must still cover it
    fe = QueryFrontend([FailingBatches()], FrontendConfig(
        target_bytes_per_job=1, batch_jobs_per_request=1, retries=0,
        tolerate_failed_blocks=1), db=db)
    r = fe.search("t1", req)
    assert r.metrics.failed_blocks == 1


def test_frontend_batches_are_geometry_pure(tmp_path):
    """Blocks with different page geometries must not share a
    SearchBlocksRequest: the querier's batcher can only stack same-(E,C)
    pages into one kernel, so a mixed batch fragments into extra
    dispatches. The meta now carries the geometry for exactly this."""
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier
    from tempo_tpu.search.columnar import PageGeometry

    db, sds_a = _frontend_db(tmp_path, n_blocks=3)
    # second geometry, same tenant/db: write blocks with (16, 8) pages
    from tempo_tpu.model import codec_for
    from tempo_tpu.search import extract_search_data
    from tempo_tpu.utils.ids import random_trace_id
    from tempo_tpu.utils.test_data import make_trace

    codec = codec_for("v2")
    db.cfg.search_geometry = PageGeometry(16, 8)
    for b in range(3):
        objs, sds = [], []
        for i in range(20):
            tid = random_trace_id()
            tr = make_trace(tid, seed=9000 + b * 100 + i)
            sd = extract_search_data(tid, tr)
            objs.append((tid, codec.marshal(tr, sd.start_s, sd.end_s),
                         sd.start_s, sd.end_s))
            sds.append(sd)
        db.write_block_direct("t1", sorted(objs), search_entries=sds)
    db.poll()
    metas = db.blocklist.metas("t1")
    geos = {(m.search_entries_per_page, m.search_kv_per_entry) for m in metas}
    assert len(geos) == 2

    q = Querier(db, Ring(), {})
    seen_batches = []
    orig = Querier.search_blocks

    def spy(self, breq):
        by_block = {m.block_id: m for m in metas}
        seen_batches.append([by_block[j.block_id] for j in breq.jobs])
        return orig(self, breq)

    Querier.search_blocks = spy
    try:
        fe = QueryFrontend([q], FrontendConfig(batch_jobs_per_request=4))
        req = _mk_req({})
        req.limit = 10_000
        fe.search("t1", req)
    finally:
        Querier.search_blocks = orig
    assert seen_batches
    for batch in seen_batches:
        batch_geos = {(m.search_entries_per_page, m.search_kv_per_entry)
                      for m in batch}
        assert len(batch_geos) == 1, "mixed-geometry batch"


def test_flush_backoff_and_sibling_isolation(tmp_path):
    """A failing completion backs off exponentially (30s→120s envelope,
    reference flush.go:359-389) and must not stop the same tenant's other
    ready completions in that sweep (VERDICT r2 #7)."""
    app = _app(tmp_path)
    ing = app.ingesters["ingester-0"]
    inst = ing.instance("t1")
    inst.FLUSH_BACKOFF_S = 0.05
    inst.FLUSH_BACKOFF_MAX_S = 0.2

    # two completing blocks for one tenant
    _push_traces(app, "t1", 5)
    inst.cut_complete_traces(force=True)
    inst.cut_block_if_ready(force=True)
    _push_traces(app, "t1", 5, seed_base=100)
    inst.cut_complete_traces(force=True)
    inst.cut_block_if_ready(force=True)
    assert len(inst.completing) == 2
    poisoned = inst.completing[0].blk.meta.block_id

    real_write = app.backend.write
    def flaky(tenant, block_id, name, data):
        if block_id == poisoned:
            raise OSError("flake")
        return real_write(tenant, block_id, name, data)
    app.backend.write = flaky

    # one sweep: the poisoned block fails + backs off, the sibling lands
    completed = ing.sweep(force=False, max_idle_s=0)
    assert len(completed) == 1 and completed[0].block_id != poisoned
    assert len(inst.completing) == 1
    c = inst.completing[0]
    assert c.backoff_s == inst.FLUSH_BACKOFF_S and c.retry_at > 0

    # within the backoff window the block is skipped, not hot-looped.
    # Pin the window open first: the real 0.05s window can elapse
    # between the sweep above and this call on a loaded host, making
    # complete_one RETRY (and raise) instead of skip — observed flaky
    # under the full suite.
    import time as _time

    c.retry_at = _time.monotonic() + 60.0
    assert inst.complete_one() is None

    # repeated failures double the backoff up to the cap
    import pytest as _pytest
    for expect in (0.1, 0.2, 0.2):
        c.retry_at = 0.0  # simulate the window elapsing
        with _pytest.raises(OSError):
            inst.complete_one()
        assert inst.completing[0].backoff_s == expect

    # backend heals → the block completes on the next eligible sweep
    app.backend.write = real_write
    inst.completing[0].retry_at = 0.0
    assert inst.complete_one() is not None
    assert not inst.completing


# ---- round 3: serving through the per-tenant fairness queue ----

def test_queue_pool_fair_interleaving():
    """With one worker and two tenants' jobs queued, execution alternates
    tenants (round-robin) instead of draining the first tenant's backlog
    first (reference v1/frontend.go per-tenant fair queue)."""
    import threading
    from tempo_tpu.modules.queue import QueueWorkerPool

    pool = QueueWorkerPool(workers=1)
    order = []
    gate = threading.Event()

    blocker = pool.submit("warm", gate.wait)  # hold the single worker
    futs = []
    for i in range(6):
        futs.append(pool.submit("loud", lambda: order.append("loud")))
    for i in range(3):
        futs.append(pool.submit("quiet", lambda: order.append("quiet")))
    gate.set()
    for f in futs:
        f.result(timeout=10)
    blocker.result(timeout=10)
    # quiet's 3 jobs are served round-robin against loud's 6: the first
    # six slots alternate, they never all queue behind loud's backlog
    assert order[:6] == ["loud", "quiet"] * 3, order
    assert order[6:] == ["loud"] * 3, order
    pool.stop()


def test_frontend_queue_429_and_http_mapping(tmp_path):
    """A tenant at max outstanding REQUESTS gets TooManyRequests,
    surfaced as HTTP 429 (reference frontend v1 max-outstanding counts
    requests, not sub-requests — a single large fan-out must not 429
    itself on an idle system)."""
    import threading
    from tempo_tpu.api.http import HTTPApi
    from tempo_tpu.modules.frontend import QueryFrontend, FrontendConfig
    from tempo_tpu.modules.queue import TooManyRequests

    app = _app(tmp_path)
    fe = QueryFrontend(app.queriers, FrontendConfig(
        query_shards=8, max_concurrent_jobs=1,
        max_outstanding_per_tenant=1))
    gate = threading.Event()
    blocker = fe.pool.submit("warm", gate.wait)  # saturate the one worker

    # first request occupies t1's single outstanding slot (jobs queued
    # behind the blocker)
    t = threading.Thread(target=lambda: fe.find_trace_by_id(
        "t1", random_trace_id()))
    t.start()
    while fe.pool.queue.outstanding("t1") < 1:
        time.sleep(0.001)

    with pytest.raises(TooManyRequests):
        fe.find_trace_by_id("t1", random_trace_id())

    # same condition through the HTTP layer -> 429, not 500
    app.frontend = fe
    api = HTTPApi(app)
    code, body = api.handle(
        "GET", "/api/traces/" + random_trace_id().hex(), {},
        {"X-Scope-OrgID": "t1"})
    assert code == 429, (code, body)

    gate.set()
    blocker.result(timeout=10)
    t.join(timeout=10)
    # slot released: the same tenant serves again (8 sub-requests fit in
    # ONE outstanding request even though the cap is 1)
    code, body = api.handle(
        "GET", "/api/traces/" + random_trace_id().hex(), {},
        {"X-Scope-OrgID": "t1"})
    assert code == 404, (code, body)  # served (unknown id), NOT 429
    fe.pool.stop()


def test_two_tenant_saturation_fairness(tmp_path):
    """Two-tenant saturation through the real frontend: a noisy tenant
    with a large backlog does not starve a quiet tenant's search — the
    quiet tenant's sub-requests interleave and finish while the noisy
    backlog is still draining (VERDICT r2 #4)."""
    import threading
    from tempo_tpu.modules.frontend import QueryFrontend, FrontendConfig

    events = []

    class SlowQuerier:
        def search_recent(self, tenant, req):
            events.append(tenant)
            time.sleep(0.005)
            return tempopb.SearchResponse()

        def search_blocks(self, breq):
            events.append(breq.tenant_id)
            time.sleep(0.005)
            return tempopb.SearchResponse()

    app = _app(tmp_path)
    # give the loud tenant a real backlog of block jobs (several blocks,
    # one page-range job each)
    for r in range(6):
        _push_traces(app, "loud", 5, seed_base=10 * r)
        app.flush_tick(force=True)
    app.poll_tick()
    db = app.reader_db
    fe = QueryFrontend([SlowQuerier()], FrontendConfig(
        max_concurrent_jobs=1, batch_jobs_per_request=1,
        target_bytes_per_job=1), db=db)

    req = _mk_req({})
    req.limit = 10**6  # no early quit: drain every job
    t_loud = threading.Thread(target=lambda: fe.search("loud", req))
    t_loud.start()
    while events.count("loud") < 2:  # loud's backlog is in the queue
        time.sleep(0.001)
    fe.search("quiet", req)  # returns while loud still has queued jobs
    quiet_done_at = len(events)
    t_loud.join()
    assert events.count("quiet") >= 1
    # quiet finished before the full loud backlog drained
    assert quiet_done_at < len(events), events
    fe.pool.stop()


def test_exclusive_flush_queue_dedupes_concurrent_sweeps(tmp_path):
    """Racing sweeps (periodic tick vs /flush vs shutdown) must not
    double-complete a block: the keyed-exclusive op queue refuses the
    duplicate enqueue while the op is queued or in flight."""
    import threading
    app = _app(tmp_path)
    ing = app.ingesters["ingester-0"]
    inst = ing.instance("t1")
    _push_traces(app, "t1", 10)
    inst.cut_complete_traces(force=True)
    inst.cut_block_if_ready(force=True)

    db = app.ingesters["ingester-0"].db
    real_complete = db.complete_block
    started = threading.Event()
    release = threading.Event()

    def slow_complete(blk, entries):
        started.set()
        release.wait(5)
        return real_complete(blk, entries)

    db.complete_block = slow_complete
    t1 = threading.Thread(target=lambda: ing.sweep(force=False, max_idle_s=0))
    t1.start()
    started.wait(5)
    # racing sweep while the op is in flight: enqueue refused, nothing to drain
    done2 = ing.sweep(force=False, max_idle_s=0)
    assert done2 == []
    release.set()
    t1.join()
    db.complete_block = real_complete
    from tempo_tpu.observability.metrics import blocks_completed
    assert len(inst.completing) == 0
    assert inst.recent and len(inst.recent) == 1  # completed exactly once


def test_force_flush_bypasses_backoff(tmp_path):
    """flush_all / shutdown must attempt backed-off blocks too — a
    scale-down must not strand a block in the local WAL because its
    retry window hadn't elapsed (code-review r3 finding)."""
    app = _app(tmp_path)
    ing = app.ingesters["ingester-0"]
    inst = ing.instance("t1")
    _push_traces(app, "t1", 5)
    inst.cut_complete_traces(force=True)
    inst.cut_block_if_ready(force=True)

    real_write = app.backend.write
    app.backend.write = lambda *a, **k: (_ for _ in ()).throw(OSError("flake"))
    assert ing.sweep(force=False, max_idle_s=0) == []
    assert inst.completing[0].retry_at > time.monotonic()  # backed off

    app.backend.write = real_write
    # NO retry_at reset: force alone must complete it
    done = ing.flush_all()
    assert len(done) == 1 and not inst.completing


def test_completing_block_stays_queryable_during_completion(tmp_path):
    """While a (long, streaming) completion is in flight the block's
    traces must stay visible to find/search — the block leaves
    `completing` only once the backend write succeeds (code-review r3
    finding; reference swaps the block out after CompleteBlock returns)."""
    import threading
    app = _app(tmp_path)
    ing = app.ingesters["ingester-0"]
    inst = ing.instance("t1")
    traces = _push_traces(app, "t1", 5)
    inst.cut_complete_traces(force=True)
    inst.cut_block_if_ready(force=True)
    tid = next(iter(traces))

    db = ing.db
    real_complete = db.complete_block
    started, release = threading.Event(), threading.Event()

    def slow_complete(blk, entries):
        started.set()
        assert release.wait(5)
        return real_complete(blk, entries)

    db.complete_block = slow_complete
    t = threading.Thread(target=lambda: ing.sweep(force=False, max_idle_s=0))
    t.start()
    try:
        assert started.wait(5)
        # completion in flight: the trace must still be findable
        partials = inst.find(tid)
        assert partials, "trace invisible while its block completes"
        req = _mk_req({})
        req.limit = 100
        from tempo_tpu.search import SearchResults
        res = SearchResults.for_request(req)
        inst.search(req, res)
        assert len(res.response().traces) == 5
    finally:
        release.set()
        t.join()
        db.complete_block = real_complete
    # and after completion it is still findable (via recent/backend)
    assert inst.find(tid)


def test_force_op_survives_nonforce_drain(tmp_path):
    """A force-enqueued flush op keeps its force semantics no matter which
    sweep drains it: the shared op queue carries the flag per op, so a
    racing periodic (non-force) drain still bypasses the block's backoff
    (code-review r3 finding)."""
    app = _app(tmp_path)
    ing = app.ingesters["ingester-0"]
    inst = ing.instance("t1")
    _push_traces(app, "t1", 5)
    inst.cut_complete_traces(force=True)
    inst.cut_block_if_ready(force=True)

    real_write = app.backend.write
    app.backend.write = lambda *a, **k: (_ for _ in ()).throw(OSError("flake"))
    assert ing.sweep(force=False, max_idle_s=0) == []
    assert inst.completing[0].retry_at > time.monotonic()
    app.backend.write = real_write

    # simulate the shutdown race: flush_all enqueued the op with force,
    # but the PERIODIC sweep's drain gets to it first
    bid = inst.completing[0].blk.meta.block_id
    ing.flush_ops.enqueue(("t1", bid), 0.0, ("t1", bid, True))
    done = ing.sweep(force=False, max_idle_s=0)
    assert len(done) == 1 and not inst.completing


def test_wal_find_tolerates_concurrent_clear(tmp_path):
    """blk.find() on a cleared WAL block returns None instead of crashing
    — readers legitimately hold refs to completing blocks while the
    successful hand-off clears them."""
    app = _app(tmp_path)
    inst = app.ingesters["ingester-0"].instance("t1")
    traces = _push_traces(app, "t1", 3)
    inst.cut_complete_traces(force=True)
    tid = next(iter(traces))
    from tempo_tpu.utils.ids import pad_trace_id
    assert inst.head.find(pad_trace_id(tid)) is not None
    blk = inst.head
    blk.clear()
    assert blk.find(pad_trace_id(tid)) is None  # no AttributeError


def test_flush_all_raises_when_backend_down(tmp_path):
    """A shutdown caller must be able to distinguish 'all flushed' from
    'gave up': when the backend stays down, flush_all raises
    FlushIncompleteError (with the successfully-flushed list attached)
    instead of returning as if the WAL were safe to delete (advisor r3)."""
    from tempo_tpu.modules.ingester import FlushIncompleteError

    app = _app(tmp_path)
    ing = app.ingesters["ingester-0"]
    inst = ing.instance("t1")
    _push_traces(app, "t1", 3)
    inst.cut_complete_traces(force=True)
    inst.cut_block_if_ready(force=True)

    app.backend.write = lambda *a, **k: (_ for _ in ()).throw(OSError("down"))
    with pytest.raises(FlushIncompleteError) as ei:
        ing.flush_all(settle_timeout_s=2.0)
    assert ei.value.left_behind == 1
    assert ei.value.completed == []
    assert len(inst.completing) == 1  # block still in the local WAL


def test_flush_all_waits_for_inflight_completion(tmp_path):
    """flush_all must not conclude 'stalled' while a racing periodic
    sweep's drain thread holds the completion op — a streaming completion
    can take a long time, during which flush_all's own passes are no-ops
    by ExclusiveQueue dedupe (advisor r3 medium)."""
    import threading

    app = _app(tmp_path)
    ing = app.ingesters["ingester-0"]
    inst = ing.instance("t1")
    _push_traces(app, "t1", 3)
    inst.cut_complete_traces(force=True)
    inst.cut_block_if_ready(force=True)

    db = ing.db
    real_complete = db.complete_block
    started, release = threading.Event(), threading.Event()

    def slow_complete(blk, entries):
        started.set()
        assert release.wait(10)
        return real_complete(blk, entries)

    db.complete_block = slow_complete
    racer = threading.Thread(
        target=lambda: ing.sweep(force=False, max_idle_s=0))
    racer.start()
    assert started.wait(5)
    # release the slow completion shortly after flush_all starts waiting
    threading.Timer(0.3, release.set).start()
    done = ing.flush_all(settle_timeout_s=30.0)
    racer.join()
    db.complete_block = real_complete
    # the racer's completion counts as flushed state: nothing left behind
    assert not inst.completing
    assert inst.recent  # completed exactly once, queryable via recent


def test_frontend_batch_cache_sees_new_blocks(tmp_path):
    """The frontend's memoized job sharding must not serve a stale plan
    after the blocklist changes: a block added (and polled) after the
    first query must be searched by the next one (r4: _search_batches is
    cached per blocklist epoch)."""
    from tempo_tpu.model.codec import codec_for
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier
    from tempo_tpu.search.data import extract_search_data

    db, all_sds = _frontend_db(tmp_path, n_blocks=2, per_block=50)
    q = Querier(db, Ring(), {})
    fe = QueryFrontend([q], FrontendConfig())
    req = _mk_req({})
    req.limit = 10_000
    r1 = fe.search("t1", req)
    assert r1.metrics.inspected_traces == 100

    codec = codec_for("v2")
    objs, sds = [], []
    for i in range(30):
        tid = random_trace_id()
        tr = make_trace(tid, seed=9000 + i)
        sd = extract_search_data(tid, tr)
        objs.append((tid, codec.marshal(tr, sd.start_s, sd.end_s),
                     sd.start_s, sd.end_s))
        sds.append(sd)
    db.write_block_direct("t1", sorted(objs), search_entries=sds)

    r2 = fe.search("t1", req)
    assert r2.metrics.inspected_traces == 130  # new block included
    new_ids = {sd.trace_id.hex() for sd in sds}
    assert new_ids <= {t.trace_id for t in r2.traces}


def test_frontend_auto_batch_one_request_per_querier(tmp_path):
    """Default (auto) batch sizing spreads the job list over the querier
    pool — with one querier a whole-tenant search is ONE batched
    SearchBlocksRequest, not a fixed-size fan-out (r4: one request ~ one
    device sync on TPU)."""
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier

    db, _ = _frontend_db(tmp_path, n_blocks=4, per_block=40)
    q = Querier(db, Ring(), {})
    calls = []
    real = q.search_blocks
    q.search_blocks = lambda breq: (calls.append(len(breq.jobs)),
                                    real(breq))[1]
    fe = QueryFrontend([q], FrontendConfig())
    req = _mk_req({})
    req.limit = 10_000
    fe.search("t1", req)
    assert len(calls) == 1  # one request carried every job
    assert calls[0] == len(fe._block_jobs(db.blocklist.metas("t1")))


def test_search_blocks_jobs_cache_consistent(tmp_path):
    """Repeated identical SearchBlocksRequests hit the memoized job list
    and return identical results; a blocklist epoch bump invalidates the
    memo (r4: search_blocks O(blocks) host work must not repeat per
    query)."""
    from tempo_tpu import tempopb
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier

    db, all_sds = _frontend_db(tmp_path, n_blocks=3, per_block=40)
    metas = db.blocklist.metas("t1")
    breq = tempopb.SearchBlocksRequest()
    breq.tenant_id = "t1"
    req = _mk_req({})
    req.limit = 10_000
    breq.search_req.CopyFrom(req)
    for m in metas:
        j = breq.jobs.add()
        j.block_id = m.block_id
        j.encoding = m.encoding
        j.version = m.version
        j.data_encoding = m.data_encoding
    r1 = db.search_blocks(breq).response()
    r2 = db.search_blocks(breq).response()
    assert ({t.trace_id for t in r1.traces}
            == {t.trace_id for t in r2.traces})
    assert r1.metrics.inspected_traces == r2.metrics.inspected_traces == 120
    assert len(db._breq_jobs_cache) == 1
    epoch0, jobs0 = db._breq_jobs_cache.values()[0][:2]
    assert len(jobs0) == 3
    # epoch bump -> rebuild on next request
    db.blocklist.update("t1", add=[])
    db.search_blocks(breq)
    epoch1 = db._breq_jobs_cache.values()[0][0]
    assert epoch1 > epoch0


def test_search_blocks_cache_promotes_late_container(tmp_path):
    """A transient DoesNotExist (read-after-write lag: meta visible
    before the search container) must not pin a block to the slow proto
    fallback for the whole epoch — the cached entry re-probes and
    promotes on the next request (code-review r4)."""
    from tempo_tpu import tempopb
    from tempo_tpu.backend.raw import DoesNotExist
    from tempo_tpu.backend.types import NAME_SEARCH

    db, all_sds = _frontend_db(tmp_path, n_blocks=1, per_block=40)
    m = db.blocklist.metas("t1")[0]

    # hide the container: first request classifies the block as fallback
    real_read = db.backend.read
    def read_no_container(tenant, bid, name, **kw):
        if name == NAME_SEARCH:
            raise DoesNotExist(f"{bid}/{name}")
        return real_read(tenant, bid, name, **kw)
    # the header read decides _scan_job; hide it too
    from tempo_tpu.backend.types import NAME_SEARCH_HEADER
    def read_hidden(tenant, bid, name, **kw):
        if name in (NAME_SEARCH, NAME_SEARCH_HEADER):
            raise DoesNotExist(f"{bid}/{name}")
        return real_read(tenant, bid, name, **kw)
    db.backend.read = read_hidden

    breq = tempopb.SearchBlocksRequest()
    breq.tenant_id = "t1"
    req = _mk_req({})
    req.limit = 10_000
    breq.search_req.CopyFrom(req)
    j = breq.jobs.add()
    j.block_id = m.block_id
    j.encoding = m.encoding
    j.version = m.version
    j.data_encoding = m.data_encoding

    r1 = db.search_blocks(breq)
    assert db._breq_jobs_cache.values()[0][2]  # cached as fallback
    # container appears; the SAME cached request must promote it
    db.backend.read = real_read
    r2 = db.search_blocks(breq)
    entry = db._breq_jobs_cache.values()[0]
    assert not entry[2] and len(entry[1]) == 1  # promoted to a ScanJob
    assert r2.metrics.inspected_traces == 40


def test_search_blocks_cache_keyed_by_encoding(tmp_path):
    """Requests differing only in job encoding/version must not alias to
    one cached job list (code-review r4: the key carries every field
    that shapes the ScanJob)."""
    from tempo_tpu import tempopb

    db, _ = _frontend_db(tmp_path, n_blocks=1, per_block=20)
    m = db.blocklist.metas("t1")[0]

    def mk(encoding):
        breq = tempopb.SearchBlocksRequest()
        breq.tenant_id = "t1"
        req = _mk_req({})
        req.limit = 100
        breq.search_req.CopyFrom(req)
        j = breq.jobs.add()
        j.block_id = m.block_id
        j.encoding = encoding
        j.version = m.version
        j.data_encoding = m.data_encoding
        return breq

    db.search_blocks(mk(m.encoding))
    db.search_blocks(mk("gzip"))
    assert len(db._breq_jobs_cache) == 2  # distinct cache entries


def test_shutdown_surfaces_incomplete_flush(tmp_path):
    """App.shutdown must not return success while WAL data remains: the
    FlushIncompleteError re-raises AFTER the full drain so an
    orchestrator cannot tear down the WAL volume on a clean-looking
    return (code-review r4)."""
    from tempo_tpu.modules.ingester import FlushIncompleteError

    app = _app(tmp_path)
    inst = app.ingesters["ingester-0"].instance("t1")
    _push_traces(app, "t1", 3)
    inst.cut_complete_traces(force=True)
    inst.cut_block_if_ready(force=True)
    app.backend.write = lambda *a, **k: (_ for _ in ()).throw(OSError("down"))
    for ing in app.ingesters.values():
        ing.flush_all = lambda _f=ing.flush_all: _f(settle_timeout_s=1.0)
    with pytest.raises(FlushIncompleteError):
        app.shutdown()
    assert len(inst.completing) == 1


def test_windowed_search_skips_containerless_block(tmp_path):
    """A container-less block entirely outside the request window must be
    window-pruned via the meta times carried in the job — not fully
    proto-scanned — now that the frontend ships all blocks and defers
    window pruning to the executor (code-review r4)."""
    from tempo_tpu.model.codec import codec_for
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier
    from tempo_tpu.observability import metrics as obs

    db, all_sds = _frontend_db(tmp_path, n_blocks=1, per_block=20)
    in_window = db.blocklist.metas("t1")[0]

    # a second block WITHOUT search entries (no container -> proto
    # fallback path), far outside the window
    codec = codec_for("v2")
    objs = []
    for i in range(10):
        tid = random_trace_id()
        tr = make_trace(tid, seed=7000 + i)
        objs.append((tid, codec.marshal(tr, 100, 200), 100, 200))
    db.write_block_direct("t1", sorted(objs), search_entries=None)

    q = Querier(db, Ring(), {})
    fe = QueryFrontend([q], FrontendConfig())
    req = _mk_req({})
    req.limit = 10_000
    req.start = in_window.start_time
    req.end = in_window.end_time
    f0 = obs.fallback_scans.value(tenant="t1")
    r = fe.search("t1", req)
    assert obs.fallback_scans.value(tenant="t1") == f0  # no proto scan
    assert r.metrics.inspected_traces == 20  # container block only
    assert r.metrics.skipped_blocks >= 1  # the out-of-window block


# ---------------------------------------------------------------------------
# concurrent replica fan-out (reference querier.go:252-276)


class _FanoutIngester:
    """Duck-typed ingester replica with injectable delay/failure."""

    def __init__(self, name, n_traces=0, delay_s=0.0, fail=False):
        self.name = name
        self.n_traces = n_traces
        self.delay_s = delay_s
        self.fail = fail

    def search(self, tenant, req, results):
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.fail:
            raise RuntimeError(f"{self.name} down")
        for i in range(self.n_traces):
            m = tempopb.TraceSearchMetadata(
                trace_id=f"{self.name}-{i}", root_service_name=self.name,
                start_time_unix_nano=1, duration_ms=1)
            results.add(m)
        results.metrics.inspected_traces += self.n_traces

    def find_trace_by_id(self, tenant, tid):
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.fail:
            raise RuntimeError(f"{self.name} down")
        return []


def test_search_recent_fanout_is_concurrent_not_additive():
    """Three replicas × 0.4s each must cost ~0.4s, not ~1.2s."""
    from tempo_tpu.modules.querier import Querier

    ings = {f"i{k}": _FanoutIngester(f"i{k}", n_traces=1, delay_s=0.4)
            for k in range(3)}
    q = Querier(None, Ring(), ings)
    req = tempopb.SearchRequest()
    req.limit = 100
    t0 = time.monotonic()
    resp = q.search_recent("t1", req)
    elapsed = time.monotonic() - t0
    assert len(resp.traces) == 3
    assert elapsed < 0.9, f"fan-out took {elapsed:.2f}s — additive, not concurrent"


def test_search_recent_early_quit_skips_slow_straggler():
    """Limit satisfied by fast replicas: don't wait for the slow one."""
    from tempo_tpu.modules.querier import Querier

    ings = {"fast1": _FanoutIngester("fast1", n_traces=2),
            "fast2": _FanoutIngester("fast2", n_traces=2),
            "slow": _FanoutIngester("slow", n_traces=1, delay_s=2.0)}
    q = Querier(None, Ring(), ings)
    req = tempopb.SearchRequest()
    req.limit = 2
    t0 = time.monotonic()
    resp = q.search_recent("t1", req)
    elapsed = time.monotonic() - t0
    assert len(resp.traces) == 2
    assert elapsed < 1.0, f"early quit waited on the straggler ({elapsed:.2f}s)"


def test_search_recent_failed_replica_counts_failed_not_skipped():
    from tempo_tpu.modules.querier import Querier

    ings = {"ok": _FanoutIngester("ok", n_traces=2),
            "dead": _FanoutIngester("dead", fail=True)}
    q = Querier(None, Ring(), ings)
    req = tempopb.SearchRequest()
    req.limit = 100
    resp = q.search_recent("t1", req)
    assert len(resp.traces) == 2
    assert resp.metrics.failed_blocks == 1
    assert resp.metrics.skipped_blocks == 0


def test_trace_by_id_ingester_leg_concurrent():
    """The replica leg of trace-by-id fans out concurrently too."""
    from tempo_tpu.modules.querier import Querier

    ring = Ring(replication_factor=3)
    ings = {}
    for k in range(3):
        ring.register(f"i{k}")
        ings[f"i{k}"] = _FanoutIngester(f"i{k}", delay_s=0.4)

    class _NoBlocks:
        def find_trace_by_id(self, tenant, tid, bs, be):
            return None, 0

    q = Querier(_NoBlocks(), ring, ings)
    t0 = time.monotonic()
    resp = q.find_trace_by_id("t1", b"\x01" * 16, mode="ingesters")
    elapsed = time.monotonic() - t0
    assert resp.metrics.failed_blocks == 0
    assert elapsed < 0.9, f"replica leg additive ({elapsed:.2f}s)"


def test_corrupt_search_fragment_does_not_wedge_sweep(tmp_path):
    """A corrupt search_data blob is dropped at fold time; the trace
    still cuts, flushes, and reads — sweep never wedges (code-review r4:
    the lazy decode must not move a push-time reject into an infinite
    completion retry)."""
    app = _app(tmp_path)
    ing = app.ingesters["ingester-0"]
    tid = random_trace_id()
    tr = make_trace(tid, seed=1)
    app.push("t1", list(tr.batches))
    # inject a corrupt fragment alongside the good one
    from tempo_tpu.model.codec import segment_codec_for
    codec = segment_codec_for("v2")
    seg = codec.prepare_for_write(make_trace(tid, seed=2), 100, 200)
    ing.instance("t1").push(tid, seg, search_data=b"\x01\x02garbage")

    completed = app.flush_tick(force=True)
    assert completed and completed[0].total_objects >= 1
    app.poll_tick()
    assert len(app.find_trace("t1", tid).trace.batches) > 0


def test_tag_endpoints_cap_block_sweep(tmp_path):
    """Tag queries consult the newest TAG_BLOCKS_LIMIT blocks, not the
    whole corpus — a 10K-block tenant must not stage every container
    through the 64-entry LRU per tags call."""
    from tempo_tpu.modules.querier import Querier

    db, _ = _frontend_db(tmp_path, n_blocks=6, per_block=10)
    q = Querier(db, Ring(), {})
    q.TAG_BLOCKS_LIMIT = 3
    staged = []
    orig = db._search_block_for

    def counting(m):
        staged.append(m.block_id)
        return orig(m)

    db._search_block_for = counting
    resp = q.search_tags("t1")
    assert resp.tag_names  # still answers
    assert len(set(staged)) <= 3, staged
    # the consulted blocks are the NEWEST by end_time
    metas = sorted(db.blocklist.metas("t1"),
                   key=lambda m: m.end_time or 0, reverse=True)
    assert set(staged) <= {m.block_id for m in metas[:3]}


def test_tag_endpoints_cover_blocklist_poll_gap(tmp_path):
    """find() and search() already swept recently-completed blocks; the
    tag endpoints did not — so a service's tags vanished from UI
    dropdowns for a full poll interval right after flush (observed via
    the jaeger bridge in r5). Flush WITHOUT polling the reader: tag
    names and values must still be visible through the querier."""
    app = App(AppConfig(
        backend={"backend": "local", "local": {"path": str(tmp_path / "b")}},
        wal_dir=str(tmp_path / "w")))
    from tempo_tpu.utils.ids import random_trace_id
    from tempo_tpu.utils.test_data import make_trace

    for i in range(5):
        app.push("t1", list(make_trace(random_trace_id(), seed=i).batches))
    completed = app.flush_tick(force=True)
    assert completed  # blocks left the ingester...
    # ...and the reader has NOT polled: the gap under test
    assert not app.reader_db.blocklist.metas("t1")

    tags = app.queriers[0].search_tags("t1")
    assert "service.name" in tags.tag_names
    vals = app.queriers[0].search_tag_values("t1", "service.name")
    assert vals.tag_values, "tag values invisible during the poll gap"
