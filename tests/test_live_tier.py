"""Hot-tier live search (search/live_tier.py): differential identity
against the flushed-block scan, lifecycle no-dup/no-gap, tail
subscriptions, deadline/overflow degradation, gate-off noop."""

import time

import pytest

from tempo_tpu import tempopb
from tempo_tpu.db.tempodb import TempoDBConfig
from tempo_tpu.modules import App, AppConfig
from tempo_tpu.search.data import SearchData, encode_search_data
from tempo_tpu.search.live_tier import LIVE_TIER, TailSubscription
from tempo_tpu.search.results import SearchResults
from tempo_tpu.utils.test_data import make_trace


@pytest.fixture(autouse=True)
def _reset_live_tier():
    # LIVE_TIER is the process-wide singleton (most recent TempoDB's
    # config wins); leave it disabled for whatever test runs next
    yield
    LIVE_TIER.configure(enabled=False)


def _db(**kw):
    kw.setdefault("auto_mesh", False)
    kw.setdefault("search_live_tier_enabled", True)
    return TempoDBConfig(**kw)


def _req(tags=None, limit=50, **kw):
    req = tempopb.SearchRequest()
    for k, v in (tags or {}).items():
        req.tags[k] = v
    req.limit = limit
    for k, v in kw.items():
        setattr(req, k, v)
    return req


def _tid(i: int) -> bytes:
    return bytes([i]) * 16


def _traces(resp) -> list[bytes]:
    return [m.SerializeToString() for m in resp.traces]


_QUERIES = (
    {"component": "db"},
    {"service.name": "frontend"},
    {"http.status_code": "500"},
    {"component": "db", "service.name": "checkout"},
    {"nonexistent": "zz"},
)


@pytest.mark.parametrize("packed", [False, True])
def test_hot_scan_byte_identical_to_flushed_scan(tmp_path, packed):
    """The tentpole identity: searching in-flight traces through the
    hot tier returns byte-identical trace metadata to searching the
    same data after flush+poll through the backend kernel."""
    app = App(AppConfig(wal_dir=str(tmp_path / "wal"),
                        db=_db(search_packed_residency=packed)))
    for i in range(24):
        app.push("t1", list(make_trace(_tid(i), seed=i).batches))
    reqs = [_req(q) for q in _QUERIES] + [
        _req({"component": "db"}, min_duration_ms=5),
        _req({}, max_duration_ms=900),
    ]
    hot = [app.search("t1", r) for r in reqs]
    assert any(r.traces for r in hot)  # the corpus matches something
    app.flush_tick(force=True)
    app.poll_tick()
    flushed = [app.search("t1", r) for r in reqs]
    for h, f in zip(hot, flushed):
        assert _traces(h) == _traces(f)
    app.shutdown()


def test_gate_off_noop_identity(tmp_path):
    """search_live_tier_enabled=false answers byte-identically to the
    gate-on tier over the same pushed data (the legacy per-entry walk
    is the reference)."""

    def run(db_cfg, sub):
        app = App(AppConfig(wal_dir=str(tmp_path / sub), db=db_cfg))
        for i in range(16):
            app.push("t1", list(make_trace(_tid(i), seed=i).batches))
        out = [_traces(app.search("t1", _req(q))) for q in _QUERIES]
        app.shutdown()
        return out

    off = run(TempoDBConfig(auto_mesh=False), "off")
    on = run(_db(), "on")
    assert on == off


def test_no_dup_no_gap_across_flush_and_poll(tmp_path):
    """One trace answers EXACTLY once at every lifecycle stage: live
    (hot stage), cut+flushed (recently-flushed leg), and poll-visible
    (reader leg; the recent leg retires via mark_poll_visible)."""
    app = App(AppConfig(wal_dir=str(tmp_path / "wal"), db=_db()))
    tid = _tid(7)
    app.push("t1", list(make_trace(tid, seed=3).batches))
    req = _req({})  # matches everything pushed

    def hits():
        return [m.trace_id for m in app.search("t1", req).traces
                ].count(tid.hex())

    assert hits() == 1                  # live: hot-tier scan
    app.flush_tick(force=True)
    assert hits() == 1                  # flushed, not yet poll-visible
    app.poll_tick()
    assert hits() == 1                  # reader leg; recent leg retired
    # the hot stage evicted the cut trace — its live set is empty now
    assert not LIVE_TIER._tenants["t1"].entries
    app.shutdown()


def test_structural_hot_scan_matches_flushed(tmp_path):
    """Structural predicates go through the compiled plan on the hot
    stage exactly as on backend blocks — same answer pre- and
    post-flush."""
    app = App(AppConfig(
        wal_dir=str(tmp_path / "wal"),
        db=_db(search_structural_enabled=True)))
    tid = b"\x01" * 16
    tr = tempopb.Trace()
    rs = tr.batches.add()
    kv = rs.resource.attributes.add()
    kv.key = "service.name"
    kv.value.string_value = "api"
    ss = rs.scope_spans.add()
    root = ss.spans.add()
    root.trace_id = tid
    root.span_id = b"\x0a" * 8
    root.name = "root-op"
    root.kind = 2
    root.start_time_unix_nano = 1_600_000_000_000_000_000
    root.end_time_unix_nano = root.start_time_unix_nano + 500_000_000
    child = ss.spans.add()
    child.trace_id = tid
    child.span_id = b"\x0b" * 8
    child.parent_span_id = root.span_id
    child.name = "child-op"
    child.kind = 3
    child.start_time_unix_nano = root.start_time_unix_nano
    child.end_time_unix_nano = child.start_time_unix_nano + 400_000_000
    app.push("t1", [rs])
    app.push("t1", list(make_trace(_tid(2), seed=5).batches))

    from tempo_tpu.search.structural import STRUCTURAL_QUERY_TAG
    q = ('{"child": {"parent": {"tag": {"k": "service.name", '
         '"v": "api"}}, "child": {"dur": {"min_ms": 300}}}}')
    req = _req({STRUCTURAL_QUERY_TAG: q}, limit=10)
    hot = app.search("t1", req)
    assert [m.trace_id for m in hot.traces] == [tid.hex()]
    app.flush_tick(force=True)
    app.poll_tick()
    flushed = app.search("t1", req)
    assert _traces(hot) == _traces(flushed)
    app.shutdown()


def test_tail_subscription_delivery_cap_and_release(tmp_path):
    app = App(AppConfig(
        wal_dir=str(tmp_path / "wal"),
        db=_db(search_live_tail_max_subscriptions=2)))
    sub = app.tail_subscribe("t1", _req({}))
    assert sub is not None
    tid = _tid(9)
    app.push("t1", list(make_trace(tid, seed=1).batches))
    metas = sub.poll(timeout_s=5.0)
    assert [m.trace_id for m in metas] == [tid.hex()]
    # a non-matching standing query stays silent
    quiet = app.tail_subscribe("t1", _req({"nonexistent": "zz"}))
    app.push("t1", list(make_trace(_tid(10), seed=2).batches))
    assert sub.poll(timeout_s=5.0)
    assert quiet.poll(timeout_s=0.05) == []
    # per-tenant cap: third registration rejected, released slot reusable
    assert app.tail_subscribe("t1", _req({})) is None
    app.tail_unsubscribe(quiet)
    again = app.tail_subscribe("t1", _req({}))
    assert again is not None
    app.shutdown()


def test_tail_subscribe_none_when_gate_off(tmp_path):
    app = App(AppConfig(wal_dir=str(tmp_path / "wal"),
                        db=TempoDBConfig(auto_mesh=False)))
    assert app.tail_subscribe("t1", _req({})) is None
    app.shutdown()


def test_tail_queue_drops_oldest():
    sub = TailSubscription("t", _req({}), max_queue=2)
    for i in range(3):
        m = tempopb.TraceSearchMetadata()
        m.trace_id = _tid(i).hex()
        sub.offer(m)
    assert sub.dropped == 1
    got = [m.trace_id for m in sub.poll(timeout_s=0.0)]
    assert got == [_tid(1).hex(), _tid(2).hex()]  # oldest lost


def test_overflow_falls_back_to_walk():
    LIVE_TIER.configure(enabled=True, max_entries=2)
    for i in range(3):
        sd = SearchData(trace_id=_tid(i))
        sd.start_s = 1_600_000_000
        sd.end_s = sd.start_s + 1
        sd.dur_ms = 5
        sd.kvs = {"component": {"db"}}
        LIVE_TIER.absorb("t", _tid(i), encode_search_data(sd))
    results = SearchResults()
    # past max_entries: the tier declines and the caller runs the walk
    assert LIVE_TIER.search("t", _req({}), results) is False
    assert results.n_results == 0


def test_streaming_block_deadline_books_partial(tmp_path):
    from tempo_tpu.robustness import deadline as rdeadline
    from tempo_tpu.search.streaming import StreamingSearchBlock

    ssb = StreamingSearchBlock(str(tmp_path / "w.search"))
    sd = SearchData(trace_id=_tid(1))
    sd.start_s = 1_600_000_000
    sd.end_s = sd.start_s + 1
    sd.dur_ms = 5
    sd.kvs = {"component": {"db"}}
    ssb.append(_tid(1), sd)
    results = SearchResults()
    with rdeadline.start(0.001):
        time.sleep(0.01)
        ssb.search(_req({}), results)
    assert results.metrics.partial
    assert results.n_results == 0
    ssb.clear()


def test_progressive_stream_hot_first_then_done(tmp_path):
    """/api/search/stream: result frames arrive as legs land (hot tier
    first), the done frame equals the blocking /api/search answer."""
    import json as _json

    from tempo_tpu.api.http import HTTPApi

    app = App(AppConfig(wal_dir=str(tmp_path / "wal"), db=_db()))
    api = HTTPApi(app)
    hdr = {"X-Scope-OrgID": "t1"}
    tid = _tid(4)
    app.push("t1", list(make_trace(tid, seed=4).batches))
    code, body = api.handle("GET", "/api/search/stream",
                            {"limit": "10"}, hdr)
    assert code == 200
    frames = list(body.events)
    kinds = [f.split("\n", 1)[0] for f in frames]
    assert kinds[0] == "event: result" and kinds[-1] == "event: done"
    done = _json.loads(frames[-1].split("data: ", 1)[1])
    code, blocking = api.handle("GET", "/api/search", {"limit": "10"}, hdr)
    assert code == 200
    assert done["traces"] == blocking["traces"]
    app.shutdown()
