"""In-process mock S3 / GCS / Azure object stores for backend tests.

The role minio / fake-gcs-server / azurite play in the reference's e2e
suite (integration/e2e/backend/): real HTTP servers speaking enough of
each protocol to exercise the client end to end — including *verifying
request signatures* (SigV4, Azure SharedKey, GCS bearer) by independent
recomputation, so auth bugs fail tests rather than production.
"""

from __future__ import annotations

import datetime
import hashlib
import threading
import urllib.parse
import xml.sax.saxutils as sx
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tempo_tpu.backend.s3 import sign_v4
from tempo_tpu.backend.azure import sign_shared_key


def start(handler_cls, store: dict | None = None, **attrs):
    """Start a ThreadingHTTPServer on an ephemeral port. Returns
    (server, endpoint). Handler state rides on the server object."""
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    srv.store = store if store is not None else {}
    srv.lock = threading.Lock()
    for k, v in attrs.items():
        setattr(srv, k, v)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


class _Base(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # quiet
        pass

    @property
    def store(self) -> dict:
        return self.server.store

    def body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    def reply(self, status: int, data: bytes = b"", ctype="application/octet-stream",
              extra: dict | None = None):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(data)

    def parse(self):
        u = urllib.parse.urlparse(self.path)
        q = {k: v[0] for k, v in urllib.parse.parse_qs(
            u.query, keep_blank_values=True).items()}
        return urllib.parse.unquote(u.path), q

    def range_slice(self, data: bytes):
        rng = self.headers.get("Range")
        if not rng:
            return 200, data
        lo, hi = rng.split("=")[1].split("-")
        return 206, data[int(lo): int(hi) + 1]


# ---------------------------------------------------------------------------
# S3


class MockS3Handler(_Base):
    """Keys stored as '<bucket>/<key>'. Verifies SigV4 on every request."""

    def _verify(self, path: str, query: dict, payload: bytes) -> bool:
        auth = self.headers.get("Authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256"):
            return False
        parts = dict(p.strip().split("=", 1)
                     for p in auth.split(" ", 1)[1].split(","))
        cred = parts["Credential"].split("/")
        access_key, date_stamp, region = cred[0], cred[1], cred[2]
        if access_key != self.server.access_key:
            return False
        declared_sha = self.headers.get("x-amz-content-sha256", "")
        if hashlib.sha256(payload).hexdigest() != declared_sha:
            return False
        signed = parts["SignedHeaders"].split(";")
        # rebuild the extra headers sign_v4 was called with (it adds host,
        # x-amz-date, x-amz-content-sha256 itself)
        extra = {h: self.headers[h] for h in signed
                 if h not in ("host", "x-amz-date", "x-amz-content-sha256")}
        now = datetime.datetime.strptime(
            self.headers["x-amz-date"], "%Y%m%dT%H%M%SZ"
        ).replace(tzinfo=datetime.timezone.utc)
        expect = sign_v4(
            method=self.command, host=self.headers["Host"], path=path,
            query=query, headers=extra, payload_sha256=declared_sha,
            region=region, access_key=access_key,
            secret_key=self.server.secret_key, now=now)
        return expect["Authorization"] == auth and date_stamp == now.strftime("%Y%m%d")

    def _handle(self):
        path, q = self.parse()
        body = self.body()
        if not self._verify(path, q, body):
            return self.reply(403, b"<Error><Code>SignatureDoesNotMatch</Code></Error>")
        bucket, _, key = path.lstrip("/").partition("/")
        full = f"{bucket}/{key}"
        uploads = getattr(self.server, "uploads", None)
        if uploads is None:
            uploads = self.server.uploads = {}
        # multipart upload protocol (CreateMultipartUpload / UploadPart /
        # CompleteMultipartUpload), as the real service and minio speak it
        if self.command == "POST" and "uploads" in q:
            uid = hashlib.sha1(f"{full}{len(uploads)}".encode()).hexdigest()
            with self.server.lock:
                uploads[uid] = {"key": full, "parts": {}}
            xml = (f"<InitiateMultipartUploadResult><UploadId>{uid}"
                   "</UploadId></InitiateMultipartUploadResult>")
            return self.reply(200, xml.encode(), "application/xml")
        if self.command == "PUT" and "partNumber" in q and "uploadId" in q:
            uid = q["uploadId"]
            with self.server.lock:
                up = uploads.get(uid)
                if up is None or up["key"] != full:
                    return self.reply(404, b"<Error><Code>NoSuchUpload</Code></Error>")
                up["parts"][int(q["partNumber"])] = body
            etag = f'"{hashlib.md5(body).hexdigest()}"'
            return self.reply(200, extra={"ETag": etag})
        if self.command == "POST" and "uploadId" in q:
            uid = q["uploadId"]
            with self.server.lock:
                up = uploads.pop(uid, None)
                if up is None or up["key"] != full:
                    return self.reply(404, b"<Error><Code>NoSuchUpload</Code></Error>")
                self.store[full] = b"".join(
                    up["parts"][n] for n in sorted(up["parts"]))
            return self.reply(200, b"<CompleteMultipartUploadResult/>",
                              "application/xml")
        if self.command == "PUT":
            with self.server.lock:
                self.store[full] = body
            return self.reply(200)
        if self.command in ("GET", "HEAD") and q.get("list-type") == "2":
            return self._list(bucket, q)
        if self.command in ("GET", "HEAD"):
            with self.server.lock:
                data = self.store.get(full)
            if data is None:
                return self.reply(404, b"<Error><Code>NoSuchKey</Code></Error>")
            status, sliced = self.range_slice(data)
            return self.reply(status, sliced)
        if self.command == "DELETE" and "uploadId" in q:
            # AbortMultipartUpload: discard pending parts
            with self.server.lock:
                uploads.pop(q["uploadId"], None)
            return self.reply(204)
        if self.command == "DELETE":
            with self.server.lock:
                self.store.pop(full, None)
            return self.reply(204)
        return self.reply(400)

    def _list(self, bucket: str, q: dict):
        prefix = q.get("prefix", "")
        delim = q.get("delimiter", "")
        with self.server.lock:
            keys = sorted(k[len(bucket) + 1:] for k in self.store
                          if k.startswith(f"{bucket}/"))
        contents, common = [], []
        for k in keys:
            if not k.startswith(prefix):
                continue
            rest = k[len(prefix):]
            if delim and delim in rest:
                p = prefix + rest.split(delim)[0] + delim
                if p not in common:
                    common.append(p)
            else:
                contents.append(k)
        xml = ["<?xml version='1.0'?><ListBucketResult>",
               "<IsTruncated>false</IsTruncated>"]
        xml += [f"<Contents><Key>{sx.escape(k)}</Key></Contents>" for k in contents]
        xml += [f"<CommonPrefixes><Prefix>{sx.escape(p)}</Prefix></CommonPrefixes>"
                for p in common]
        xml.append("</ListBucketResult>")
        return self.reply(200, "".join(xml).encode(), "application/xml")

    do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = _handle


# ---------------------------------------------------------------------------
# GCS (JSON API)


class MockGCSHandler(_Base):
    def _authed(self) -> bool:
        want = getattr(self.server, "token", "")
        if not want:
            return True
        return self.headers.get("Authorization", "") == f"Bearer {want}"

    def _handle(self):
        import json
        path, q = self.parse()
        body = self.body()
        if not self._authed():
            return self.reply(401, b"{}", "application/json")
        sessions = getattr(self.server, "sessions", None)
        if sessions is None:
            sessions = self.server.sessions = {}
        # resumable upload protocol: session create + Content-Range chunks
        if (self.command == "POST" and path.startswith("/upload/storage/v1/b/")
                and q.get("uploadType") == "resumable"):
            sid = hashlib.sha1(f"{q['name']}{len(sessions)}".encode()).hexdigest()
            with self.server.lock:
                sessions[sid] = {"name": q["name"], "data": b""}
            loc = f"http://{self.headers['Host']}/upload/session/{sid}"
            return self.reply(200, b"{}", "application/json",
                              extra={"Location": loc})
        if self.command == "PUT" and path.startswith("/upload/session/"):
            sid = path.rsplit("/", 1)[1]
            with self.server.lock:
                sess = sessions.get(sid)
                if sess is None:
                    return self.reply(404, b"{}", "application/json")
                rng = self.headers.get("Content-Range", "")
                # "bytes start-end/total", "bytes */total"
                spec, _, total = rng.partition("/")
                if not spec.startswith("bytes"):
                    return self.reply(400, b"{}", "application/json")
                if body:
                    start_s = spec.split(" ", 1)[1].split("-")[0]
                    if start_s != "*" and int(start_s) != len(sess["data"]):
                        return self.reply(400, b"{}", "application/json")
                    sess["data"] += body
                if total != "*" and len(sess["data"]) == int(total):
                    self.store[sess["name"]] = sess["data"]
                    del sessions[sid]
                    return self.reply(200, b"{}", "application/json")
            return self.reply(308, b"", "application/json")
        if self.command == "DELETE" and path.startswith("/upload/session/"):
            # cancel resumable upload: real GCS answers 499
            sid = path.rsplit("/", 1)[1]
            with self.server.lock:
                sessions.pop(sid, None)
            return self.reply(499, b"", "application/json")
        if self.command == "POST" and path.startswith("/upload/storage/v1/b/"):
            with self.server.lock:
                self.store[q["name"]] = body
            return self.reply(200, b"{}", "application/json")
        if path.startswith("/storage/v1/b/") and "/o/" in path:
            key = path.split("/o/", 1)[1]
            if self.command == "GET":
                with self.server.lock:
                    data = self.store.get(key)
                if data is None:
                    return self.reply(404, b"{}", "application/json")
                status, sliced = self.range_slice(data)
                return self.reply(status, sliced)
            if self.command == "DELETE":
                with self.server.lock:
                    existed = self.store.pop(key, None)
                return self.reply(204 if existed is not None else 404)
        if self.command == "GET" and path.startswith("/storage/v1/b/"):
            prefix, delim = q.get("prefix", ""), q.get("delimiter", "")
            with self.server.lock:
                keys = sorted(self.store)
            items, prefixes = [], []
            for k in keys:
                if not k.startswith(prefix):
                    continue
                rest = k[len(prefix):]
                if delim and delim in rest:
                    p = prefix + rest.split(delim)[0] + delim
                    if p not in prefixes:
                        prefixes.append(p)
                else:
                    items.append({"name": k})
            doc = {"items": items, "prefixes": prefixes}
            return self.reply(200, json.dumps(doc).encode(), "application/json")
        return self.reply(400, b"{}", "application/json")

    do_GET = do_PUT = do_POST = do_DELETE = _handle


# ---------------------------------------------------------------------------
# Azure Blob


class MockAzureHandler(_Base):
    def _verify(self, path: str, q: dict) -> bool:
        auth = self.headers.get("Authorization", "")
        headers = {k: v for k, v in self.headers.items()}
        expect = sign_shared_key(
            method=self.command, account=self.server.account, path=path,
            query=q, headers=headers, key_b64=self.server.key)
        return auth == expect

    def _handle(self):
        path, q = self.parse()
        body = self.body()
        if not self._verify(path, q):
            return self.reply(403, b"<Error>AuthenticationFailed</Error>")
        container, _, key = path.lstrip("/").partition("/")
        full = f"{container}/{key}"
        if q.get("comp") == "list":
            return self._list(container, q)
        blocks = getattr(self.server, "blocks", None)
        if blocks is None:
            blocks = self.server.blocks = {}
        # block-blob protocol: Put Block + Put Block List
        if self.command == "PUT" and q.get("comp") == "block":
            with self.server.lock:
                blocks[(full, q["blockid"])] = body
            return self.reply(201)
        if self.command == "PUT" and q.get("comp") == "blocklist":
            import re

            ids = re.findall(r"<Latest>([^<]+)</Latest>", body.decode())
            with self.server.lock:
                try:
                    data = b"".join(blocks[(full, b)] for b in ids)
                except KeyError:
                    return self.reply(400, b"<Error>InvalidBlockList</Error>")
                self.store[full] = data
                for b in ids:
                    blocks.pop((full, b), None)
            return self.reply(201)
        if self.command == "PUT":
            with self.server.lock:
                self.store[full] = body
            return self.reply(201)
        if self.command == "GET":
            with self.server.lock:
                data = self.store.get(full)
            if data is None:
                return self.reply(404, b"<Error>BlobNotFound</Error>")
            status, sliced = self.range_slice(data)
            return self.reply(status, sliced)
        if self.command == "DELETE":
            with self.server.lock:
                existed = self.store.pop(full, None)
            return self.reply(202 if existed is not None else 404)
        return self.reply(400)

    def _list(self, container: str, q: dict):
        prefix = q.get("prefix", "")
        delim = q.get("delimiter", "")
        with self.server.lock:
            keys = sorted(k[len(container) + 1:] for k in self.store
                          if k.startswith(f"{container}/"))
        blobs, prefixes = [], []
        for k in keys:
            if not k.startswith(prefix):
                continue
            rest = k[len(prefix):]
            if delim and delim in rest:
                p = prefix + rest.split(delim)[0] + delim
                if p not in prefixes:
                    prefixes.append(p)
            else:
                blobs.append(k)
        xml = ["<?xml version='1.0' encoding='utf-8'?><EnumerationResults><Blobs>"]
        xml += [f"<Blob><Name>{sx.escape(b)}</Name></Blob>" for b in blobs]
        xml += [f"<BlobPrefix><Name>{sx.escape(p)}</Name></BlobPrefix>"
                for p in prefixes]
        xml.append("</Blobs><NextMarker/></EnumerationResults>")
        return self.reply(200, "".join(xml).encode(), "application/xml")

    do_GET = do_PUT = do_DELETE = _handle
