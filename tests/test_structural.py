"""Structural query engine (ISSUE 14): IR parsing, the span-segment
substrate, and the differential contract — random IR trees over random
corpora must answer byte-for-byte identically through every engine path
(single / batched / coalesced / mesh / dist + both host routes) vs the
plain-python reference evaluator (`structural.eval_host`), packed
residency on and off, breaker-forced host routes included."""

from __future__ import annotations

import json
import random

import numpy as np
import pytest

from tempo_tpu import robustness, tempopb
from tempo_tpu.backend.local import LocalBackend
from tempo_tpu.db import TempoDB, TempoDBConfig
from tempo_tpu.search import ir, structural
from tempo_tpu.search import packing as packing_mod
from tempo_tpu.search.batcher import host_scan
from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
from tempo_tpu.search.data import (
    SearchData,
    SpanData,
    decode_search_data,
    encode_search_data,
    search_data_matches,
)
from tempo_tpu.search.multiblock import MultiBlockEngine, compile_multi
from tempo_tpu.search.structural import (
    STRUCTURAL,
    STRUCTURAL_QUERY_TAG,
    compile_structural,
    eval_host,
    structural_query,
)

E_GEO = PageGeometry(entries_per_page=64, kv_per_entry=8)

_SVCS = ["api", "db", "auth", "cache", "web"]
_OPS = ["op0", "op1", "op2"]


@pytest.fixture(autouse=True)
def _structural_on():
    """Each test runs with the gate ON (the default-off contract has its
    own tests) and leaves the process gate as it found it."""
    prev = STRUCTURAL.enabled
    prev_stack = STRUCTURAL.stack_enabled
    prev_shard = STRUCTURAL.shard_spans
    prev_bucket = STRUCTURAL.bucket_enabled
    prev_bucket_max = STRUCTURAL.bucket_max_nodes
    prev_remainder = STRUCTURAL.remainder_pages
    STRUCTURAL.enabled = True
    packing_prev = packing_mod.PACKING.enabled
    yield
    STRUCTURAL.enabled = prev
    STRUCTURAL.stack_enabled = prev_stack
    STRUCTURAL.shard_spans = prev_shard
    STRUCTURAL.bucket_enabled = prev_bucket
    STRUCTURAL.bucket_max_nodes = prev_bucket_max
    STRUCTURAL.remainder_pages = prev_remainder
    packing_mod.PACKING.enabled = packing_prev
    robustness.BREAKER.reset()


def _corpus(seed: int, n: int = 150, max_spans: int = 9):
    rng = random.Random(seed)
    entries = []
    for i in range(n):
        sd = SearchData(trace_id=i.to_bytes(2, "big").rjust(16, b"\x00"))
        sd.start_s = 1_600_000_000 + i
        sd.end_s = sd.start_s + rng.randint(0, 10)
        sd.dur_ms = rng.randint(1, 5000)
        sd.root_service = rng.choice(_SVCS)
        sd.kvs = {
            "service.name": {sd.root_service},
            "env": {"prod" if i % 2 else "dev"},
        }
        for _ in range(rng.randint(0, max_spans)):
            s = len(sd.spans)
            sd.spans.append(SpanData(
                parent=(-1 if s == 0 or rng.random() < 0.2
                        else rng.randrange(s)),
                dur_ms=rng.randint(1, 1000),
                kind=rng.randint(0, 5),
                kvs={"service.name": {rng.choice(_SVCS)},
                     "name": {rng.choice(_OPS)}},
            ))
        entries.append(sd)
    return entries


def _rand_span(rng: random.Random, depth: int) -> ir.SpanExpr:
    choices = ["tag", "dur", "kind"]
    if depth > 0:
        choices += ["and", "or", "not", "child", "desc"]
    op = rng.choice(choices)
    if op == "tag":
        return ir.SpanTag(rng.choice(["service.name", "name", "nope"]),
                          rng.choice(["a", "p", "op", "db", ""]))
    if op == "dur":
        lo = rng.randint(0, 800)
        return ir.SpanDur(lo, lo + rng.randint(0, 800))
    if op == "kind":
        return ir.SpanKind(rng.randint(0, 5))
    if op in ("and", "or"):
        args = tuple(_rand_span(rng, depth - 1)
                     for _ in range(rng.randint(1, 3)))
        return ir.SpanAnd(args) if op == "and" else ir.SpanOr(args)
    if op == "not":
        return ir.SpanNot(_rand_span(rng, depth - 1))
    if op == "child":
        return ir.ChildOf(_rand_span(rng, depth - 1),
                          _rand_span(rng, depth - 1))
    return ir.DescOf(_rand_span(rng, depth - 1),
                     _rand_span(rng, depth - 1))


def _rand_trace(rng: random.Random, depth: int = 2) -> ir.TraceExpr:
    choices = ["exists", "count", "quantile", "tag", "dur"]
    if depth > 0:
        choices += ["and", "or", "not"]
    op = rng.choice(choices)
    if op == "exists":
        return ir.Exists(_rand_span(rng, 2))
    if op == "count":
        return ir.Count(_rand_span(rng, 1),
                        rng.choice(ir.CMP_OPS), rng.randint(0, 4))
    if op == "quantile":
        qn, qd = rng.choice([(1, 2), (9, 10), (99, 100), (1, 4)])
        return ir.Quantile(_rand_span(rng, 1), qn, qd,
                           rng.choice(ir.CMP_OPS), rng.randint(0, 900))
    if op == "tag":
        return ir.TraceTag(rng.choice(["service.name", "env", "nope"]),
                           rng.choice(["a", "prod", "dev", ""]))
    if op == "dur":
        lo = rng.randint(0, 4000)
        return ir.TraceDur(lo, lo + rng.randint(0, 4000))
    if op in ("and", "or"):
        args = tuple(_rand_trace(rng, depth - 1)
                     for _ in range(rng.randint(1, 3)))
        return ir.TraceAnd(args) if op == "and" else ir.TraceOr(args)
    return ir.TraceNot(_rand_trace(rng, depth - 1))


def _reparam_span(e: ir.SpanExpr, rng: random.Random) -> ir.SpanExpr:
    """Same tree SHAPE (ops, arity, comparison operators), fresh leaf
    parameters — the 'N dashboards running the same saved query with
    different filters' load plan-shape stacking exists for."""
    if isinstance(e, ir.SpanTag):
        return ir.SpanTag(rng.choice(["service.name", "name", "nope"]),
                          rng.choice(["a", "p", "op", "db", ""]))
    if isinstance(e, ir.SpanDur):
        lo = rng.randint(0, 800)
        return ir.SpanDur(lo, lo + rng.randint(0, 800))
    if isinstance(e, ir.SpanKind):
        return ir.SpanKind(rng.randint(0, 5))
    if isinstance(e, ir.SpanAnd):
        return ir.SpanAnd(tuple(_reparam_span(a, rng) for a in e.args))
    if isinstance(e, ir.SpanOr):
        return ir.SpanOr(tuple(_reparam_span(a, rng) for a in e.args))
    if isinstance(e, ir.SpanNot):
        return ir.SpanNot(_reparam_span(e.arg, rng))
    if isinstance(e, ir.ChildOf):
        return ir.ChildOf(_reparam_span(e.parent, rng),
                          _reparam_span(e.child, rng))
    return ir.DescOf(_reparam_span(e.anc, rng),
                     _reparam_span(e.span, rng))


def _reparam(e: ir.TraceExpr, rng: random.Random) -> ir.TraceExpr:
    if isinstance(e, ir.TraceTag):
        return ir.TraceTag(rng.choice(["service.name", "env", "nope"]),
                           rng.choice(["a", "prod", "dev", ""]))
    if isinstance(e, ir.TraceDur):
        lo = rng.randint(0, 4000)
        return ir.TraceDur(lo, lo + rng.randint(0, 4000))
    if isinstance(e, ir.Exists):
        return ir.Exists(_reparam_span(e.of, rng))
    if isinstance(e, ir.Count):
        return ir.Count(_reparam_span(e.of, rng), e.op, rng.randint(0, 4))
    if isinstance(e, ir.Quantile):
        qn, qd = rng.choice([(1, 2), (9, 10), (99, 100), (1, 4)])
        return ir.Quantile(_reparam_span(e.of, rng), qn, qd, e.op,
                           rng.randint(0, 900))
    if isinstance(e, ir.TraceAnd):
        return ir.TraceAnd(tuple(_reparam(a, rng) for a in e.args))
    if isinstance(e, ir.TraceOr):
        return ir.TraceOr(tuple(_reparam(a, rng) for a in e.args))
    return ir.TraceNot(_reparam(e.arg, rng))


def _expected_ids(expr, entries) -> set:
    return {sd.trace_id for sd in entries if eval_host(expr, sd)}


def _scan_ids(batch, eng, mq, entries) -> tuple[int, set]:
    count, _ins, scores, idx = eng.scan(batch, mq)
    E = batch.blocks[0].geometry.entries_per_page
    got = set()
    for s, i in zip(scores.tolist(), idx.tolist()):
        if s < 0:
            break
        p, e = divmod(i, E)
        bi = int(batch.page_block[p])
        lp = p - batch.page_offset[bi]
        got.add(bytes(batch.blocks[bi].trace_ids[lp, e]))
    return int(count), got


def _mk_req(expr, limit: int = 4096) -> tempopb.SearchRequest:
    req = tempopb.SearchRequest()
    req.limit = limit
    structural.attach_query(req, expr)
    return req


# ---------------------------------------------------------------- IR


def test_ir_parse_roundtrip():
    src = ('{"and": [{"count": {"of": {"child": {"parent": {"tag": '
           '{"k": "service.name", "v": "api"}}, "child": {"dur": '
           '{"min_ms": 100}}}}, "op": ">", "n": 1}}, '
           '{"quantile": {"of": {"kind": "server"}, "q": "0.9", '
           '"op": ">=", "ms": 250}}]}')
    expr = ir.parse(src)
    again = ir.parse(ir.to_json(expr))
    assert again == expr
    # the quoted transport form round-trips too
    assert ir.parse_quoted(ir.quote(ir.to_json(expr))) == expr


@pytest.mark.parametrize("src,path_frag", [
    ("{", "$"),
    ('{"nope": 1}', "$"),
    ('{"and": []}', "$.and"),
    ('{"count": {"of": {"dur": {}}, "op": "~", "n": 1}}', "$.count.op"),
    ('{"exists": {"tag": {"k": "", "v": "x"}}}', "$.exists.tag.k"),
    ('{"quantile": {"of": {"dur": {}}, "q": "1.5", "ms": 1}}',
     "$.quantile.q"),
    ('{"exists": {"kind": "banana"}}', "$.exists.kind"),
    ('{"dur": {"min_ms": 10, "max_ms": 1}}', "$.dur"),
    ('{"and": [{"dur": {"bogus": 1}}]}', "$.and[0].dur"),
])
def test_ir_parse_errors_carry_json_path(src, path_frag):
    with pytest.raises(ir.IRSyntaxError) as e:
        ir.parse(src)
    assert path_frag in str(e.value)


def test_ir_quantile_q1_roundtrips():
    """q=1.0 must serialize to a re-parseable form ("1", never the
    float-format artifact "1.") — attach_query stows to_json output in
    the transport tag, so an unparseable form fails a VALID query."""
    for q in ("1.0", "1", "0.5", "0.999", "0.25"):
        src = ('{"quantile": {"of": {"dur": {"min_ms": 1}}, "q": "%s", '
               '"op": ">=", "ms": 10}}' % q)
        expr = ir.parse(src)
        again = ir.parse(ir.to_json(expr))
        assert (again.q_num * expr.q_den
                == expr.q_num * again.q_den), q  # same rational
        req = _mk_req(expr)
        assert structural_query(req) is not None


def test_ir_node_budget_enforced():
    deep = {"dur": {"min_ms": 1}}
    for _ in range(ir.MAX_NODES + 1):
        deep = {"not": deep}
    with pytest.raises(ir.IRSyntaxError) as e:
        ir.parse(json.dumps(deep))
    assert "limit" in str(e.value)


# ------------------------------------------------- wire + container


def test_search_data_span_codec_roundtrip_and_legacy_compat():
    sd = _corpus(3, n=5)[2]
    assert sd.spans  # seed chosen to carry spans
    sd2 = decode_search_data(encode_search_data(sd), sd.trace_id)
    assert [(s.parent, s.dur_ms, s.kind, s.kvs) for s in sd2.spans] == \
        [(s.parent, s.dur_ms, s.kind, s.kvs) for s in sd.spans]
    # legacy payload (no span section) decodes to spans == []
    legacy = SearchData(trace_id=sd.trace_id, start_s=1, end_s=2,
                        dur_ms=3, kvs={"a": {"b"}})
    dec = decode_search_data(encode_search_data(legacy), sd.trace_id)
    assert dec.spans == []
    # span-less encode is byte-identical to the legacy wire form
    assert encode_search_data(legacy) == encode_search_data(
        SearchData(trace_id=sd.trace_id, start_s=1, end_s=2, dur_ms=3,
                   kvs={"a": {"b"}}))


def test_columnar_span_segment_roundtrips():
    entries = _corpus(11, n=100)
    pages = ColumnarPages.build(entries, E_GEO)
    assert pages.has_spans
    # codec round-trip
    p2 = ColumnarPages.from_bytes(pages.to_bytes())
    for name, _ in ColumnarPages._SPAN_ARRAYS:
        assert np.array_equal(getattr(p2, name), getattr(pages, name)), name
    # to_entries (compaction) preserves span rows incl. parent links
    back = pages.to_entries()
    assert len(back) == len(entries)
    for orig, rt in zip(entries, back):
        assert [(s.parent, s.dur_ms, s.kind) for s in rt.spans] == \
            [(s.parent, s.dur_ms, s.kind) for s in orig.spans]
        for s_o, s_r in zip(orig.spans, rt.spans):
            assert s_r.kvs == s_o.kvs
    # gate-off build (no spans captured) stays byte-identical legacy
    legacy_entries = _corpus(11, n=100)
    for sd in legacy_entries:
        sd.spans = []
    legacy = ColumnarPages.build(legacy_entries, E_GEO)
    assert not legacy.has_spans
    assert b"span_trace" not in legacy.to_bytes()


def test_slice_pages_remaps_span_segment():
    entries = _corpus(13, n=200)
    pages = ColumnarPages.build(entries, E_GEO)
    E = E_GEO.entries_per_page
    sl = pages.slice_pages(1, 2)
    expr = ir.parse('{"count": {"of": {"tag": {"k": "name", "v": "op"}},'
                    ' "op": ">", "n": 2}}')
    eng = MultiBlockEngine(top_k=512)
    batch = eng.stage([sl])
    req = _mk_req(expr)
    mq = compile_multi([sl], req, cache_on=batch)
    mq.structural = compile_structural(expr, [sl], cache_on=batch)
    count, got = _scan_ids(batch, eng, mq, entries)
    want = _expected_ids(expr, entries[E:3 * E])
    assert got == want and count == len(want)


# the acceptance triple (ISSUE 14): a parent-child query, a descendant
# query, and a count(span) > N aggregate — asserted correct through
# EVERY engine path (batched/host in _check_paths; mesh, dist, single,
# and the serving path each run the triple below)
_ACCEPTANCE_TRIPLE = (
    '{"child": {"parent": {"tag": {"k": "service.name", "v": "api"}}, '
    '"child": {"dur": {"min_ms": 200}}}}',
    '{"desc": {"anc": {"tag": {"k": "service.name", "v": "db"}}, '
    '"span": {"kind": "client"}}}',
    '{"count": {"of": {"tag": {"k": "name", "v": "op"}}, "op": ">", '
    '"n": 3}}',
)


# ---------------------------------------------- engine-path identity


def _check_paths(entries, exprs, packed: bool, mesh=None, seed=0):
    """Compiled-vs-host identity over the batched device path AND the
    byte-identical host route, one staged batch, many queries."""
    packing_mod.PACKING.enabled = packed
    # two blocks with distinct dictionaries + one span-less block: the
    # assembly must handle group maps and absent segments
    half = len(entries) // 2
    b1 = ColumnarPages.build(entries[:half], E_GEO)
    b2 = ColumnarPages.build(entries[half:], E_GEO)
    spanless = [SearchData(trace_id=(10_000 + i).to_bytes(16, "big"),
                           start_s=1, end_s=2, dur_ms=100,
                           kvs={"env": {"prod"}}) for i in range(5)]
    b3 = ColumnarPages.build(spanless, E_GEO)
    blocks = [b1, b2, b3]
    eng = MultiBlockEngine(top_k=512, mesh=mesh)
    host = eng.stage_host(blocks)
    batch = eng.place(host)
    for expr in exprs:
        req = _mk_req(expr)
        mq = compile_multi(blocks, req, cache_on=batch)
        assert mq is not None
        mq.structural = compile_structural(
            expr, blocks, cache_on=batch, staged_dicts=batch.staged_dicts)
        want = _expected_ids(expr, entries + spanless)
        count, got = _scan_ids(batch, eng, mq, entries)
        assert got == want, (ir.to_json(expr), packed, "device")
        assert count == len(want)
        # breaker-style host route: host-only compile, CPU-pinned kernel
        mq_h = compile_multi(blocks, req, cache_on=batch, host_only=True)
        mq_h.structural = compile_structural(expr, blocks, host_only=True)
        hcount, _hi, hscores, hidx = host_scan(host, mq_h, 512)
        assert hcount == len(want), (ir.to_json(expr), packed, "host")
        E = E_GEO.entries_per_page
        hgot = set()
        for s, i in zip(hscores.tolist(), hidx.tolist()):
            if s < 0:
                break
            p, e = divmod(i, E)
            bi = int(host.page_block[p])
            lp = p - host.page_offset[bi]
            hgot.add(bytes(host.blocks[bi].trace_ids[lp, e]))
        assert hgot == want


def test_fixed_queries_all_paths_unpacked():
    entries = _corpus(21)
    exprs = [
        # the acceptance triple: parent-child, descendant, count
        ir.parse('{"child": {"parent": {"tag": {"k": "service.name", '
                 '"v": "api"}}, "child": {"dur": {"min_ms": 200}}}}'),
        ir.parse('{"desc": {"anc": {"tag": {"k": "service.name", '
                 '"v": "db"}}, "span": {"kind": "client"}}}'),
        ir.parse('{"count": {"of": {"tag": {"k": "name", "v": "op"}}, '
                 '"op": ">", "n": 3}}'),
        ir.parse('{"quantile": {"of": {"dur": {"min_ms": 1}}, '
                 '"q": "0.9", "op": ">=", "ms": 500}}'),
        ir.parse('{"and": [{"tag": {"k": "env", "v": "prod"}}, '
                 '{"not": {"exists": {"kind": 4}}}]}'),
    ]
    _check_paths(entries, exprs, packed=False)


def test_fixed_queries_all_paths_packed():
    entries = _corpus(22)
    exprs = [
        ir.parse('{"child": {"parent": {"tag": {"k": "service.name", '
                 '"v": "a"}}, "child": {"dur": {"min_ms": 100}}}}'),
        ir.parse('{"count": {"of": {"kind": "server"}, "op": ">=", '
                 '"n": 2}}'),
        ir.parse('{"and": [{"dur": {"min_ms": 1000}}, {"exists": '
                 '{"tag": {"k": "name", "v": "op1"}}}]}'),
    ]
    _check_paths(entries, exprs, packed=True)


@pytest.mark.parametrize("packed", [False, True])
def test_differential_fuzz_compiled_vs_host(packed):
    """The property: ANY random IR tree over ANY random corpus answers
    identically on the compiled device path, the host route, and the
    reference evaluator."""
    rng = random.Random(40_000 + packed)
    for round_i in range(6):
        entries = _corpus(500 + round_i, n=80)
        exprs = [_rand_trace(rng) for _ in range(5)]
        _check_paths(entries, exprs, packed=packed,
                     seed=round_i)


def _check_stacked(entries, template, rng, packed: bool, mesh=None,
                   n_variants: int = 5):
    """Plan-shape stacking differential: a random same-shape query
    group answers bit-for-bit identically fused (stack_queries +
    coalesced kernel), solo (multi_scan_kernel), and on the host
    reference evaluator. Returns the group size actually stacked."""
    from tempo_tpu.search.engine import fetch_coalesced_out
    from tempo_tpu.search.multiblock import stack_queries

    packing_mod.PACKING.enabled = packed
    half = len(entries) // 2
    b1 = ColumnarPages.build(entries[:half], E_GEO)
    b2 = ColumnarPages.build(entries[half:], E_GEO)
    spanless = [SearchData(trace_id=(10_000 + i).to_bytes(16, "big"),
                           start_s=1, end_s=2, dur_ms=100,
                           kvs={"env": {"prod"}}) for i in range(5)]
    blocks = [b1, b2, ColumnarPages.build(spanless, E_GEO)]
    eng = MultiBlockEngine(top_k=512, mesh=mesh)
    batch = eng.stage(blocks)
    variants = [template] + [_reparam(template, rng)
                             for _ in range(n_variants - 1)]
    mqs = []
    for expr in variants:
        req = _mk_req(expr)
        mq = compile_multi(blocks, req, cache_on=batch)
        mq.structural = compile_structural(
            expr, blocks, cache_on=batch,
            staged_dicts=batch.staged_dicts)
        mq._expr = expr
        mqs.append(mq)
    # leaf dedup can shift a variant's plan (two leaves collapsing to
    # one term index): stack exactly the same-plan members — the same
    # grouping stack_group_key enforces in the coalescer
    base = mqs[0].structural.plan
    group = [mq for mq in mqs if mq.structural.plan == base]
    assert len(group) >= 2, "reparam produced no same-plan peer"
    cq = stack_queries(group)
    assert cq.structural is not None and cq.structural.plan == base
    counts, _ins, scores, idx = fetch_coalesced_out(
        eng.coalesced_scan_async(batch, cq, 512))
    all_entries = entries + spanless
    E = E_GEO.entries_per_page
    for qi, mq in enumerate(group):
        got = set()
        for s, i in zip(scores[qi].tolist(), idx[qi].tolist()):
            if s < 0:
                break
            p, e = divmod(i, E)
            if p >= batch.n_pages:
                continue
            bi = int(batch.page_block[p])
            if bi < 0:
                continue
            lp = p - batch.page_offset[bi]
            got.add(bytes(batch.blocks[bi].trace_ids[lp, e]))
        want = _expected_ids(mq._expr, all_entries)
        scount, sgot = _scan_ids(batch, eng, mq, all_entries)
        assert got == want == sgot, (ir.to_json(mq._expr), packed,
                                     len(got), len(want), len(sgot))
        assert int(counts[qi]) == len(want) == scount
    return len(group)


@pytest.mark.parametrize("packed", [False, True])
def test_differential_fuzz_stacked_plans(packed):
    """The stacking property: ANY random same-shape structural query
    group answers identically coalesced (one fused dispatch), solo, and
    on the reference evaluator — packed residency on and off."""
    rng = random.Random(60_000 + packed)
    for round_i in range(4):
        entries = _corpus(700 + round_i, n=80)
        template = _rand_trace(rng)
        _check_stacked(entries, template, rng, packed=packed)


def test_stacked_plans_on_mesh_with_sharded_spans():
    """Stacking composes with segment-aligned span sharding: the fused
    dispatch over sharded span columns answers identically to solo
    dispatches, the replicated layout, and the host evaluator."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple (forced host) devices")
    from tempo_tpu.parallel import make_mesh

    rng = random.Random(99)
    entries = _corpus(801, n=160)
    mesh = make_mesh()
    for template in [ir.parse(s) for s in _ACCEPTANCE_TRIPLE[:2]] \
            + [_rand_trace(rng)]:
        STRUCTURAL.shard_spans = True
        try:
            _check_stacked(entries, template, rng, packed=False,
                           mesh=mesh)
        finally:
            STRUCTURAL.shard_spans = False


def test_sharded_span_segment_layout_and_identity():
    """The reshard itself: trace-whole chunks, local coordinates, and
    byte-identical answers sharded vs replicated vs host (the mesh path
    runs it end to end when >1 device is available)."""
    import jax

    entries = _corpus(77, n=400)
    blocks = [ColumnarPages.build(entries, E_GEO)]
    eng = MultiBlockEngine(top_k=512)
    host = eng.stage_host(blocks)
    span_cat = host.span_cat
    assert span_cat is not None
    P_pages = int(host.page_block.shape[0])
    E = E_GEO.entries_per_page
    n_sh = 4
    STRUCTURAL.shard_spans = True
    try:
        sh = STRUCTURAL.shard_span_segment(span_cat, n_sh, P_pages, E)
    finally:
        STRUCTURAL.shard_spans = False
    assert sh is not None
    per_shard = sh["span_trace"].shape[0] // n_sh
    pp = P_pages // n_sh
    # every live span sits in the chunk of its trace's page shard, with
    # a local trace index and a parent inside the same chunk
    for s in range(n_sh):
        chunk = slice(s * per_shard, (s + 1) * per_shard)
        tr = sh["span_trace"][chunk]
        live = tr >= 0
        assert (tr[live] < pp * E).all()
        par = sh["span_parent"][chunk][live]
        assert ((par >= -1) & (par < per_shard)).all()
    # per-trace verdict identity vs the replicated layout: per-shard
    # span bytes shrink to ~1/P of the replicated staging
    rep_bytes = sum(int(v.nbytes) for k, v in span_cat.items()
                    if k.startswith("span_"))
    sh_bytes = sum(int(v.nbytes) for k, v in sh.items()
                   if k.startswith("span_")) // n_sh
    assert sh_bytes < rep_bytes
    # disabled gate: one attribute read, None (replicated layout kept)
    assert STRUCTURAL.shard_span_segment(span_cat, n_sh, P_pages, E) \
        is None


def test_serving_path_stacks_concurrent_same_plan_queries(tmp_path):
    """8 concurrent same-plan-shape structural searches through the
    FULL serving path fuse (dispatches/request well below 1 for the
    structural leg), byte-identical to the same queries run serially,
    and the stack metric + /debug ratio say so."""
    import threading

    from tempo_tpu.observability import metrics as obs

    entries = _corpus(91, n=120)
    db = _mkdb(tmp_path, entries,
               search_structural_stack_enabled=True,
               search_coalesce_window_s=0.05)
    svcs = ["api", "db", "auth", "cache", "web", "api", "db", "auth"]
    exprs = [ir.parse(
        '{"child": {"parent": {"tag": {"k": "service.name", "v": "%s"}},'
        ' "child": {"dur": {"min_ms": %d}}}}' % (svc, 50 + 50 * i))
        for i, svc in enumerate(svcs)]
    def canon(resp):
        # device_seconds is a wall-clock measurement — legitimately
        # different run to run; everything else must be byte-identical
        resp.metrics.device_seconds = 0
        return resp.SerializeToString()

    serial = []
    for e in exprs:
        r = _mk_req(e, limit=1000)
        serial.append(canon(db.search("t", r).response()))
    co = db.batcher.coalescer
    base_stacked = co.structural_stacked
    stacked0 = obs.structural_stack_events.value(result="stacked")
    out = [None] * len(exprs)
    barrier = threading.Barrier(len(exprs))

    def one(i):
        r = _mk_req(exprs[i], limit=1000)
        barrier.wait()
        out[i] = canon(db.search("t", r).response())

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(len(exprs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(len(exprs)):
        assert out[i] == serial[i], f"query {i} diverged under stacking"
    assert co.structural_stacked > base_stacked, "no structural fusion"
    assert obs.structural_stack_events.value(result="stacked") > stacked0
    stats = co.stats()
    assert stats["structural_stack_ratio"] > 0
    # /debug/scan surfaces the same coalesce block
    dbg = db.batcher.debug_stats()
    assert dbg["coalesce"]["structural_stacked"] == co.structural_stacked


def test_stacking_disabled_keeps_solo_flush_and_counts_it(tmp_path):
    """The noop contract of the stacking gate: disabled keeps the exact
    solo-flush behavior and books result=solo_disabled."""
    from tempo_tpu.observability import metrics as obs

    entries = _corpus(92, n=60)
    db = _mkdb(tmp_path, entries)  # stack gate OFF
    assert STRUCTURAL.stack_enabled is False
    solo0 = obs.structural_stack_events.value(result="solo_disabled")
    expr = ir.parse(_ACCEPTANCE_TRIPLE[2])
    req = _mk_req(expr, limit=1000)
    got = {bytes.fromhex(m.trace_id)
           for m in db.search("t", req).response().traces}
    assert got == _expected_ids(expr, entries)
    assert obs.structural_stack_events.value(result="solo_disabled") \
        > solo0
    co = db.batcher.coalescer
    assert co.structural_stacked == 0


def test_mesh_dist_path_matches_host():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple (forced host) devices")
    from tempo_tpu.parallel import make_mesh

    entries = _corpus(31)
    rng = random.Random(77)
    exprs = [ir.parse(s) for s in _ACCEPTANCE_TRIPLE] + [_rand_trace(rng)]
    _check_paths(entries, exprs, packed=False, mesh=make_mesh())


def test_distributed_scan_engine_path():
    """The `dist` path: DistributedScanEngine shards one block's pages
    over the mesh; span columns replicate and the structural verdict
    enters the sharded scan page-sharded."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple (forced host) devices")
    from tempo_tpu.parallel import DistributedScanEngine, make_mesh
    from tempo_tpu.search.pipeline import compile_query

    entries = _corpus(45, n=100)
    pages = ColumnarPages.build(entries, E_GEO)
    eng = DistributedScanEngine(make_mesh(), top_k=512)
    sp = eng.stage(pages)
    assert sp.span_device is not None
    for src in (_ACCEPTANCE_TRIPLE):
        expr = ir.parse(src)
        req = _mk_req(expr)
        cq = compile_query(pages.key_dict, pages.val_dict, req,
                           cache_on=pages)
        cq.structural = compile_structural(expr, [pages], cache_on=pages)
        count, _ins, scores, idx = eng.scan_staged(sp, cq)
        want = _expected_ids(expr, entries)
        E = E_GEO.entries_per_page
        got = set()
        for s, i in zip(scores.tolist(), idx.tolist()):
            if s < 0:
                break
            p, e = divmod(i, E)
            if p < pages.n_pages:
                got.add(bytes(pages.trace_ids[p, e]))
        assert got == want and count == len(want), src


def test_distributed_scan_engine_sharded_spans():
    """The `dist` path with search_structural_shard_spans: span columns
    stage chunk-per-shard (span_sharded=True) and the acceptance triple
    answers byte-identically to the host reference."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple (forced host) devices")
    from tempo_tpu.parallel import DistributedScanEngine, make_mesh
    from tempo_tpu.search.pipeline import compile_query

    entries = _corpus(46, n=600)
    pages = ColumnarPages.build(entries, E_GEO)
    STRUCTURAL.shard_spans = True
    try:
        eng = DistributedScanEngine(make_mesh(), top_k=1024)
        sp = eng.stage(pages)
        assert sp.span_device is not None and sp.span_sharded
        for src in _ACCEPTANCE_TRIPLE:
            expr = ir.parse(src)
            req = _mk_req(expr)
            cq = compile_query(pages.key_dict, pages.val_dict, req,
                               cache_on=pages)
            cq.structural = compile_structural(expr, [pages],
                                               cache_on=pages)
            count, _ins, scores, idx = eng.scan_staged(sp, cq)
            want = _expected_ids(expr, entries)
            E = E_GEO.entries_per_page
            got = set()
            for s, i in zip(scores.tolist(), idx.tolist()):
                if s < 0:
                    break
                p, e = divmod(i, E)
                if p < pages.n_pages:
                    got.add(bytes(pages.trace_ids[p, e]))
            assert got == want and count == len(want), src
    finally:
        STRUCTURAL.shard_spans = False


def test_single_block_engine_path():
    from tempo_tpu.search.engine import ScanEngine, stage
    from tempo_tpu.search.pipeline import compile_query

    entries = _corpus(41, n=90)
    pages = ColumnarPages.build(entries, E_GEO)
    eng = ScanEngine(top_k=512)
    sp = stage(pages)
    assert sp.span_device is not None
    E = E_GEO.entries_per_page
    for src in _ACCEPTANCE_TRIPLE + (
            '{"count": {"of": {"child": {"parent": {"kind": "server"}, '
            '"child": {"dur": {"min_ms": 50}}}}, "op": ">=", "n": 1}}',):
        expr = ir.parse(src)
        req = _mk_req(expr)
        cq = compile_query(pages.key_dict, pages.val_dict, req,
                           cache_on=pages)
        cq.structural = compile_structural(expr, [pages], cache_on=pages)
        count, _ins, scores, idx = eng.scan_staged(sp, cq)
        want = _expected_ids(expr, entries)
        got = set()
        for s, i in zip(scores.tolist(), idx.tolist()):
            if s < 0:
                break
            p, e = divmod(i, E)
            got.add(bytes(pages.trace_ids[p, e]))
        assert got == want and count == len(want), src

        # single-block host route (breaker fallback): byte-identical
        from tempo_tpu.search.backend_search_block import host_scan_single

        cq_h = compile_query(pages.key_dict, pages.val_dict, req,
                             cache_on=pages, host_only=True)
        cq_h.structural = compile_structural(expr, [pages],
                                             cache_on=pages,
                                             host_only=True)
        hcount, _hi, _hs, _hx = host_scan_single(pages, cq_h, 512)
        assert hcount == len(want), src


# ---------------------------------------------- serving path (TempoDB)


def _mkdb(tmp_path, entries, **cfg_kw) -> TempoDB:
    cfg_kw.setdefault("auto_mesh", False)
    cfg_kw.setdefault("search_structural_enabled", True)
    be = LocalBackend(str(tmp_path / "blocks"))
    db = TempoDB(be, str(tmp_path / "wal"), TempoDBConfig(**cfg_kw))
    half = len(entries) // 2
    for chunk in (entries[:half], entries[half:]):
        db.write_block_direct(
            "t", [(sd.trace_id, encode_search_data(sd), sd.start_s,
                   sd.end_s) for sd in chunk],
            search_entries=chunk)
    return db


def test_tempodb_serving_path_with_coalescer_and_breaker_route(tmp_path):
    entries = _corpus(51, n=120)
    db = _mkdb(tmp_path, entries)
    expr = ir.parse('{"and": [{"child": {"parent": {"tag": {"k": '
                    '"service.name", "v": "a"}}, "child": {"dur": '
                    '{"min_ms": 100}}}}, {"tag": {"k": "env", '
                    '"v": ""}}]}')
    req = _mk_req(expr, limit=1000)
    req.explain = True
    want = _expected_ids(expr, entries)
    res = db.search("t", req)
    got = {bytes.fromhex(m.trace_id) for m in res.results()} \
        if hasattr(res, "results") else \
        {bytes.fromhex(m.trace_id) for m in res.response().traces}
    assert got == want
    # explain carries the compiled plan tree with per-node timings
    stats = json.loads(res.response().metrics.query_stats_json)
    ops = [n["op"] for n in stats["structural"]["nodes"]]
    assert "child" in ops and all("device_ms" in n
                                  for n in stats["structural"]["nodes"])
    # the acceptance triple through the serving (coalescer-enabled)
    # path too
    for src in _ACCEPTANCE_TRIPLE:
        e2 = ir.parse(src)
        r2 = _mk_req(e2, limit=1000)
        got2 = {bytes.fromhex(m.trace_id)
                for m in db.search("t", r2).response().traces}
        assert got2 == _expected_ids(e2, entries), src
    # breaker open: the whole serving path answers through the
    # byte-identical host route
    robustness.BREAKER.reset()
    robustness.BREAKER.threshold = 1
    robustness.BREAKER.record_fault("timeout", mode="batched")
    assert robustness.BREAKER.state == "open"
    req2 = _mk_req(expr, limit=1000)
    res2 = db.search("t", req2)
    got2 = {bytes.fromhex(m.trace_id) for m in res2.response().traces}
    assert got2 == want
    robustness.BREAKER.reset()


def test_live_and_fallback_paths_share_reference_semantics():
    """search_data_matches (live/WAL scans) and model.matches (proto
    fallback) both evaluate the host reference semantics."""
    entries = _corpus(61, n=20)
    expr = ir.parse('{"exists": {"tag": {"k": "name", "v": "op2"}}}')
    req = _mk_req(expr)
    for sd in entries:
        assert search_data_matches(sd, req) == eval_host(expr, sd)


# ------------------------------------------------------ HTTP surface


def test_http_api_structural_queries(tmp_path):
    from tempo_tpu.api.http import HTTPApi
    from tempo_tpu.modules import App, AppConfig
    from tempo_tpu.utils.test_data import make_trace

    app = App(AppConfig(
        wal_dir=str(tmp_path / "wal"),
        db=TempoDBConfig(search_structural_enabled=True,
                         auto_mesh=False)))
    api = HTTPApi(app)
    hdr = {"X-Scope-OrgID": "t1"}

    # parent-linked trace: root server span + slow child under it
    tid = b"\x01" * 16
    tr = tempopb.Trace()
    rs = tr.batches.add()
    kv = rs.resource.attributes.add()
    kv.key = "service.name"
    kv.value.string_value = "api"
    ss = rs.scope_spans.add()
    root = ss.spans.add()
    root.trace_id = tid
    root.span_id = b"\x0a" * 8
    root.name = "root-op"
    root.kind = 2
    root.start_time_unix_nano = 1_600_000_000_000_000_000
    root.end_time_unix_nano = root.start_time_unix_nano + 500_000_000
    child = ss.spans.add()
    child.trace_id = tid
    child.span_id = b"\x0b" * 8
    child.parent_span_id = root.span_id
    child.name = "child-op"
    child.kind = 3
    child.start_time_unix_nano = root.start_time_unix_nano
    child.end_time_unix_nano = child.start_time_unix_nano + 400_000_000
    app.push("t1", [rs])
    # a second, non-matching trace
    tid2 = b"\x02" * 16
    app.push("t1", list(make_trace(tid2, seed=5).batches))

    q = ('{"child": {"parent": {"tag": {"k": "service.name", '
         '"v": "api"}}, "child": {"dur": {"min_ms": 300}}}}')
    # live (recent) path
    code, body = api.handle("GET", "/api/search",
                            {"q": q, "limit": "10"}, hdr)
    assert code == 200, body
    assert [t["traceId"] for t in body.get("traces", [])] == [tid.hex()]
    # flushed backend path
    api.handle("GET", "/flush", {}, hdr)
    app.reader_db.poll()
    code, body = api.handle("GET", "/api/search",
                            {"q": q, "limit": "10", "explain": "1"}, hdr)
    assert code == 200, body
    assert [t["traceId"] for t in body.get("traces", [])] == [tid.hex()]
    assert "structural" in body.get("queryStats", {})

    # malformed IR: 400 with the JSON-path diagnostic, never a 500
    code, body = api.handle("GET", "/api/search",
                            {"q": '{"count": {"of": {"dur": {}}, '
                                  '"op": "~", "n": 1}}'}, hdr)
    assert code == 400 and "$.count.op" in body["error"]
    code, body = api.handle("GET", "/api/search", {"q": "{bogus"}, hdr)
    assert code == 400 and "structural" in body["error"]
    app.shutdown()


def test_http_gate_off_rejects_structural(tmp_path):
    from tempo_tpu.api.http import HTTPApi
    from tempo_tpu.modules import App, AppConfig

    app = App(AppConfig(wal_dir=str(tmp_path / "wal"),
                        db=TempoDBConfig(auto_mesh=False)))
    assert STRUCTURAL.enabled is False  # App configured the gate OFF
    api = HTTPApi(app)
    code, body = api.handle(
        "GET", "/api/search",
        {"q": '{"dur": {"min_ms": 1}}'}, {"X-Scope-OrgID": "t1"})
    assert code == 400 and "disabled" in body["error"]
    app.shutdown()


# ------------------------------------------------------ noop contract


def test_gate_off_is_true_noop(tmp_path):
    STRUCTURAL.enabled = False
    # extraction captures nothing; containers match the legacy bytes
    entries = _corpus(71, n=30)
    for sd in entries:
        sd.spans = []
    legacy = ColumnarPages.build(entries, E_GEO)
    assert not legacy.has_spans
    # stack_host stages no span columns when the gate is off
    eng = MultiBlockEngine(top_k=64)
    pages = ColumnarPages.build(_corpus(71, n=30), E_GEO)  # HAS spans
    host = eng.stage_host([pages])
    assert host.span_cat is None
    # the gated entry point reads one attribute and answers None for
    # legacy requests...
    req = tempopb.SearchRequest()
    req.tags["service.name"] = "api"
    assert structural_query(req) is None
    # ...and REFUSES a structural request against the disabled gate at
    # this shared altitude (gRPC and protocol paths included) — never a
    # silent legacy-scan superset
    from tempo_tpu.api.params import InvalidArgument

    req2 = tempopb.SearchRequest()
    req2.tags[STRUCTURAL_QUERY_TAG] = "ignored"
    with pytest.raises(InvalidArgument, match="disabled"):
        structural_query(req2)


def test_structural_query_parse_cache_and_invalid_tag():
    from tempo_tpu.api.params import InvalidArgument

    expr = ir.parse('{"dur": {"min_ms": 5}}')
    req = _mk_req(expr)
    assert structural_query(req) == expr
    assert structural_query(req) is structural_query(req)  # cached
    bad = tempopb.SearchRequest()
    bad.tags[STRUCTURAL_QUERY_TAG] = "%7Bnot-json"
    with pytest.raises(InvalidArgument):
        structural_query(bad)


def test_request_roundtrip_via_params():
    """The reserved tag survives the frontend <-> querier URL form."""
    from urllib.parse import parse_qs

    from tempo_tpu.api.params import (build_search_request,
                                      parse_search_request)

    expr = ir.parse('{"exists": {"tag": {"k": "service.name", '
                    '"v": "a b=c"}}}')
    req = _mk_req(expr, limit=7)
    qs = build_search_request(req)
    back = parse_search_request(
        {k: v[0] for k, v in parse_qs(qs).items()})
    assert structural_query(back) == expr
    assert back.limit == 7
