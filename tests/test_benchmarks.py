"""The bench/load harnesses must stay runnable (SURVEY.md §4 load/perf
parity) — tiny-scale executions asserting shape, not speed."""

from __future__ import annotations

import json


def test_micro_benchmarks_run(capsys):
    from benchmarks import micro

    micro.bench_wal_append(n=20)
    micro.bench_block_write_read(n=20)
    micro.bench_compaction(n=40, n_blocks=4)
    lines = [json.loads(ln) for ln in capsys.readouterr().out.strip().splitlines()]
    benches = {ln["bench"] for ln in lines}
    assert {"wal_append", "block_write", "block_read", "compaction"} <= benches
    assert all(ln["value"] > 0 for ln in lines)
    codecs = {ln.get("codec") for ln in lines if "codec" in ln}
    from tempo_tpu.encoding.v2.compression import encoding_usable

    want = {c for c in ("none", "snappy", "lz4", "zstd", "gzip")
            if encoding_usable(c)}
    assert want == codecs


def test_load_smoke_scenario(capsys):
    from benchmarks import load

    rc = load.main(["smoke", "--vus", "2", "--duration", "1.5"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["passed"]
    assert out["write"]["requests"] > 0 and out["write"]["error_rate"] == 0.0
    assert out["read"]["requests"] > 0
    assert out["read"]["error_rate"] == 0.0  # reads really succeeded


def test_load_stress_scenario(capsys):
    from benchmarks import load

    rc = load.main(["stress", "--stages", "1:1,3:1"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["passed"]
    assert out["peak_vus"] == 3 and out["write"]["requests"] > 0
