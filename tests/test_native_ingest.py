"""Native ingest walker (tt_ingest_regroup) — differential + hostile.

The C++ single-pass regroup/extract must agree with the Python walk on
EVERY observable: span→trace/batch/scope assignment (parse-equivalent
segments), search-data bytes (byte-identical), time ranges, span counts,
and the generator series derived from the summary rows. The r5
differential fuzz caught a real bug in the Python path (upb wrapper id()
reuse crossing destinations) — keep it running.
"""

import random

import pytest

from tempo_tpu import tempopb
from tempo_tpu.model.codec import segment_codec_for, CURRENT_ENCODING
from tempo_tpu.modules.distributor import Distributor
from tempo_tpu.modules.generator import MetricsGenerator
from tempo_tpu.ops import native
from tempo_tpu.search.data import encode_search_data
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.utils.test_data import make_trace

pytestmark = pytest.mark.skipif(
    not native.available() or native.ingest_regroup([], 0) is None,
    reason="native library unavailable")


def _interleaved_batches(rng, n_tids=4, n_traces=3):
    batches = []
    tids = [random_trace_id() for _ in range(rng.randint(1, n_tids))]
    for _ in range(rng.randint(1, n_traces)):
        tr = make_trace(rng.choice(tids), seed=rng.randint(0, 10_000))
        for b in tr.batches:
            for ss in b.scope_spans:
                for sp in ss.spans:
                    if rng.random() < 0.3:
                        sp.trace_id = rng.choice(tids)
            batches.append(b)
    return batches


def test_differential_regroup_extract():
    codec = segment_codec_for(CURRENT_ENCODING)
    rng = random.Random(0)
    for it in range(40):
        batches = _interleaved_batches(rng)
        budget = rng.choice([64, 256, 1024, 1 << 30])
        blobs = [b.SerializeToString() for b in batches]
        n_n, items, _ = native.ingest_regroup(blobs, budget)
        by_trace, n_p, sds = Distributor._regroup_extract(batches, budget)
        assert n_n == n_p and len(items) == len(by_trace)
        for tid, start_s, end_s, seg, sd_b in items:
            sd = sds[tid]
            assert sd_b == encode_search_data(sd), (it, budget, tid.hex())
            assert (start_s, end_s) == (sd.start_s, sd.end_s)
            want = codec.prepare_for_write(by_trace[tid], sd.start_s,
                                           sd.end_s)
            t1, t2 = tempopb.Trace(), tempopb.Trace()
            t1.ParseFromString(seg[8:])
            t2.ParseFromString(want[8:])
            assert t1.SerializeToString() == t2.SerializeToString(), it
            assert seg[:8] == want[:8]


def test_differential_span_section_vs_python_walk():
    """ISSUE 15 native span ingest: with the structural gate asking for
    span rows, tt_ingest_regroup2 emits search-data payloads whose SPAN
    SECTION is byte-identical to the Python walk (regroup + per-trace
    collect_span_rows + encode_search_data) over the differential
    corpus — parent resolution by raw span id, string_value-only
    service names, walk-order caps, kv-per-span caps, all of it.
    Skip-not-fail: a stale .so without the symbol skips."""
    from tempo_tpu.search.data import collect_span_rows

    if native.ingest_regroup([], 0, spans=True) is None:
        pytest.skip("native .so predates tt_ingest_regroup2")
    rng = random.Random(15)
    for it in range(40):
        batches = _interleaved_batches(rng)
        budget = rng.choice([64, 256, 1024, 1 << 30])
        max_spans = rng.choice([1, 3, 512])
        max_kvs = rng.choice([1, 2, 16])
        blobs = [b.SerializeToString() for b in batches]
        n_n, items, _ = native.ingest_regroup(
            blobs, budget, spans=True, max_spans=max_spans,
            max_span_kvs=max_kvs)
        by_trace, n_p, sds = Distributor._regroup_extract(batches, budget)
        for tid, trace in by_trace.items():
            sds[tid].spans = collect_span_rows(
                trace, max_spans=max_spans, max_kvs=max_kvs)
        assert n_n == n_p and len(items) == len(by_trace)
        for tid, _s, _e, _seg, sd_b in items:
            assert sd_b == encode_search_data(sds[tid]), \
                (it, budget, max_spans, max_kvs, tid.hex())


def test_span_section_gate_off_byte_identical_to_legacy():
    """flags=0 (and the legacy symbol) emit NO span section — the wire
    form with the structural gate off is byte-identical to pre-span
    builds."""
    rng = random.Random(7)
    batches = _interleaved_batches(rng)
    blobs = [b.SerializeToString() for b in batches]
    _, legacy_items, _ = native.ingest_regroup(blobs, 1024)
    _, flag0_items, _ = native.ingest_regroup(blobs, 1024, spans=False)
    assert [it[4] for it in legacy_items] == [it[4] for it in flag0_items]


def test_distributor_native_span_path_end_to_end(tmp_path):
    """With search_structural_enabled, the distributor keeps the native
    fast path (no Python walk) and the ingested blocks answer
    structural queries — proving the span rows actually flowed."""
    from tempo_tpu.db import TempoDBConfig
    from tempo_tpu.modules import App, AppConfig
    from tempo_tpu.search import ir, structural
    from tempo_tpu.search.structural import STRUCTURAL

    if native.ingest_regroup([], 0, spans=True) is None:
        pytest.skip("native .so predates tt_ingest_regroup2")
    app = App(AppConfig(
        wal_dir=str(tmp_path / "wal"),
        db=TempoDBConfig(search_structural_enabled=True,
                         auto_mesh=False)))
    try:
        assert STRUCTURAL.enabled
        tid = b"\x03" * 16
        tr = tempopb.Trace()
        rs = tr.batches.add()
        kv = rs.resource.attributes.add()
        kv.key = "service.name"
        kv.value.string_value = "api"
        ss = rs.scope_spans.add()
        root = ss.spans.add()
        root.trace_id = tid
        root.span_id = b"\x0a" * 8
        root.name = "root-op"
        root.kind = 2
        root.start_time_unix_nano = 1_600_000_000_000_000_000
        root.end_time_unix_nano = root.start_time_unix_nano + 500_000_000
        child = ss.spans.add()
        child.trace_id = tid
        child.span_id = b"\x0b" * 8
        child.parent_span_id = root.span_id
        child.name = "child-op"
        child.start_time_unix_nano = root.start_time_unix_nano
        child.end_time_unix_nano = child.start_time_unix_nano + 400_000_000
        app.push("t1", [rs])
        expr = ir.parse(
            '{"child": {"parent": {"tag": {"k": "service.name",'
            ' "v": "api"}}, "child": {"dur": {"min_ms": 300}}}}')
        req = tempopb.SearchRequest()
        req.limit = 10
        structural.attach_query(req, expr)
        res = app.search("t1", req)
        assert [m.trace_id for m in res.traces] == [tid.hex()]
    finally:
        app.shutdown()


def test_differential_generator_series():
    """Summary-row feed produces byte-identical exposition output to the
    proto-walk feed (spanmetrics + service graphs) — including for
    non-string service.name values, which both feeds must label with the
    stringified AnyValue ('true', '123'), never an empty string."""
    batches = []
    for i in range(30):
        batches.extend(make_trace(random_trace_id(), seed=i).batches)
    for field, val in (("int_value", 123), ("bool_value", True),
                       ("double_value", 2.5)):
        b = tempopb.ResourceSpans()
        kv = b.resource.attributes.add()
        kv.key = "service.name"
        setattr(kv.value, field, val)
        sp = b.scope_spans.add().spans.add()
        sp.trace_id = random_trace_id()
        sp.span_id = b"\x05" * 8
        sp.name = "op-nonstr"
        sp.kind = tempopb.Span.SPAN_KIND_SERVER
        sp.start_time_unix_nano = 10
        sp.end_time_unix_nano = 20
        batches.append(b)
    g1, g2 = MetricsGenerator(), MetricsGenerator()
    g1.push_spans("t", batches)
    blobs = [b.SerializeToString() for b in batches]
    _, items, summaries = native.ingest_regroup(blobs, 1024)
    g2.push_summary_blob("t", summaries, [it[0] for it in items])
    assert g1.collect("t") == g2.collect("t")


def test_double_attr_repr_parity():
    """code-review r5: native must format double attribute values with
    CPython's repr rule (fixed notation for exponents in [-4,16)), not
    to_chars' shortest-form — 2e5 is '200000.0', never '2e+05'."""
    from tempo_tpu.search.data import _any_value_str, decode_search_data

    rng = random.Random(0)
    vals = [2e5, 1e7, 1e15, 1e16, 1e-4, 1e-5, 1.5, 2.0, 0.1, -3.25e17,
            9999999999999998.0, -0.0, 0.0, 1.5e-5]
    vals += [rng.uniform(-1e20, 1e20) for _ in range(300)]
    vals += [rng.uniform(-1e-6, 1e-6) for _ in range(200)]
    for v in vals:
        b = tempopb.ResourceSpans()
        kv = b.resource.attributes.add()
        kv.key = "d"
        kv.value.double_value = v
        ss = b.scope_spans.add()
        sp = ss.spans.add()
        sp.trace_id = b"T" * 16
        sp.name = "x"
        sp.start_time_unix_nano = 1
        sp.end_time_unix_nano = 2
        _, items, _ = native.ingest_regroup([b.SerializeToString()], 1 << 30)
        got = decode_search_data(items[0][4], b"T" * 16).kvs.get("d")
        assert got == {_any_value_str(kv.value)}, (v, got)


def test_thousands_of_scopes_one_trace():
    """code-review r5: the (batch, scope) destination key must not
    overflow on valid inputs with huge scope counts (was a segfault)."""
    b = tempopb.ResourceSpans()
    kv = b.resource.attributes.add()
    kv.key = "service.name"
    kv.value.string_value = "s"
    for i in range(2300):
        ss = b.scope_spans.add()
        sp = ss.spans.add()
        sp.trace_id = b"T" * 16
        sp.name = f"op{i}"
        sp.start_time_unix_nano = 1
        sp.end_time_unix_nano = 2
    n, items, _ = native.ingest_regroup([b.SerializeToString()], 1 << 30)
    assert n == 2300 and len(items) == 1
    t = tempopb.Trace()
    t.ParseFromString(items[0][3][8:])
    assert sum(len(ss.spans) for bb in t.batches
               for ss in bb.scope_spans) == 2300


def test_huge_varint_length_is_clean_error():
    """code-review r5: a 10-byte varint LEN near 2^64 must not wrap the
    bounds check into a std::length_error abort — clean -2 error."""
    evil = bytes([0x2A]) + b"\xff" * 9 + b"\x01"  # name field, huge len
    span = b"\x0a\x10" + b"T" * 16 + evil
    scope = b"\x12" + bytes([len(span)]) + span
    rs = b"\x12" + bytes([len(scope)]) + scope
    with pytest.raises(RuntimeError):
        native.ingest_regroup([rs], 256)


def test_invalid_trace_id_raises_typed_error():
    b = tempopb.ResourceSpans()
    ss = b.scope_spans.add()
    sp = ss.spans.add()
    sp.trace_id = b"x" * 17  # longer than 128 bits
    with pytest.raises(native.InvalidTraceId):
        native.ingest_regroup([b.SerializeToString()], 1024)


def test_hostile_bytes_never_crash():
    """Garbage inputs → clean error (the distributor then falls back to
    the Python walk, whose proto parse raises the canonical error)."""
    rng = random.Random(7)
    good = make_trace(random_trace_id(), seed=1).batches[0] \
        .SerializeToString()
    for _ in range(300):
        blob = bytearray(good)
        for _ in range(rng.randint(1, 12)):
            blob[rng.randrange(len(blob))] = rng.randrange(256)
        try:
            native.ingest_regroup([bytes(blob)], 256)
        except (RuntimeError, native.InvalidTraceId):
            pass  # clean structured failure is fine
    # truncations
    for cut in range(0, len(good), 7):
        try:
            native.ingest_regroup([good[:cut]], 256)
        except (RuntimeError, native.InvalidTraceId):
            pass


def test_end_to_end_push_search_roundtrip(tmp_path):
    """Through App.push (native path active): flushed traces come back
    by id and by tag search — the walker's segments are real segments."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from tempo_tpu.modules import App, AppConfig

    app = App(AppConfig(
        backend={"backend": "local", "local": {"path": str(tmp_path / "b")}},
        wal_dir=str(tmp_path / "w")))
    assert app.distributor._use_native
    tids = [random_trace_id() for _ in range(8)]
    for i, tid in enumerate(tids):
        app.push("t1", list(make_trace(tid, seed=i).batches))
    app.flush_tick(force=True)
    app.poll_tick()
    for tid in tids:
        resp = app.find_trace("t1", tid)
        assert resp.trace.batches, tid.hex()
    req = tempopb.SearchRequest()
    req.limit = 100
    found = {m.trace_id for m in app.search("t1", req).traces}
    assert found == {t.hex() for t in tids}
    app.shutdown()


def test_differential_rich_corpus():
    """Exotic shapes the plain corpus lacks: unicode keys/names, empty
    and 300-char names, int64 extremes, doubles, bytes/array/kvlist
    attributes (unindexed both paths), events, links, trace_state,
    dropped counts — native and Python walks must agree byte-for-byte
    on search data and parse-equivalently on segments."""
    import os as _os

    codec = segment_codec_for(CURRENT_ENCODING)
    rng = random.Random(42)

    def rich_batch(tids):
        b = tempopb.ResourceSpans()
        b.schema_url = "https://opentelemetry.io/schemas/1.4.0"
        kv = b.resource.attributes.add()
        kv.key = "service.name"
        kv.value.string_value = rng.choice(["svc-α", "svc-b", ""])
        kv2 = b.resource.attributes.add()
        kv2.key = "host.id"
        kv2.value.int_value = rng.randint(-2**60, 2**60)
        kv3 = b.resource.attributes.add()
        kv3.key = "blob"
        kv3.value.bytes_value = _os.urandom(5)
        ss = b.scope_spans.add()
        ss.scope.name = "lib"
        ss.scope.version = "1.2.3"
        for _ in range(rng.randint(1, 5)):
            sp = ss.spans.add()
            sp.trace_id = rng.choice(tids)
            sp.span_id = _os.urandom(8)
            sp.trace_state = "vendor=1"
            if rng.random() < 0.5:
                sp.parent_span_id = _os.urandom(8)
            sp.name = rng.choice(["op-ü", "", "x" * 300])
            sp.kind = rng.randint(0, 5)
            sp.start_time_unix_nano = rng.randint(0, 2**62)
            # end < start included deliberately (clock skew is valid
            # client input): duration must clamp to max(0, end-start)
            # identically on the native and Python paths — the Python
            # walk used to raise struct.error, the native walker used to
            # saturate the unsigned underflow to 0xFFFFFFFF
            sp.end_time_unix_nano = max(
                0, sp.start_time_unix_nano + rng.randint(-10**12, 10**12))
            sp.status.code = rng.randint(0, 2)
            sp.status.message = "boom"
            a = sp.attributes.add()
            a.key = "℘-key"
            a.value.double_value = rng.choice([2e5, -0.0, 1e-7, 3.14])
            a2 = sp.attributes.add()
            a2.key = "arr"
            a2.value.array_value.values.add().string_value = "in-array"
            a3 = sp.attributes.add()
            a3.key = "kl"
            e = a3.value.kvlist_value.values.add()
            e.key = "k"
            e.value.bool_value = True
            ev = sp.events.add()
            ev.name = "evt"
            ev.time_unix_nano = 7
            ln = sp.links.add()
            ln.trace_id = _os.urandom(16)
            ln.span_id = _os.urandom(8)
            sp.dropped_attributes_count = 3
        return b

    for it in range(30):
        tids = [_os.urandom(16) for _ in range(rng.randint(1, 3))]
        batches = [rich_batch(tids) for _ in range(rng.randint(1, 4))]
        budget = rng.choice([32, 200, 1 << 30])
        blobs = [x.SerializeToString() for x in batches]
        n_n, items, _ = native.ingest_regroup(blobs, budget)
        by_trace, n_p, sds = Distributor._regroup_extract(batches, budget)
        assert n_n == n_p and len(items) == len(by_trace), it
        for tid, start_s, end_s, seg, sd_b in items:
            sd = sds[tid]
            assert sd_b == encode_search_data(sd), (it, budget)
            want = codec.prepare_for_write(by_trace[tid], sd.start_s,
                                           sd.end_s)
            t1, t2 = tempopb.Trace(), tempopb.Trace()
            t1.ParseFromString(seg[8:])
            t2.ParseFromString(want[8:])
            assert t1.SerializeToString() == t2.SerializeToString(), it
