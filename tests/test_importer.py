"""Reference-block importer (VERDICT r4 #5).

The fixture writer below produces a block in the GO v2 format straight
from the spec (page framing page.go:22-57, object framing
object.go:20-47, 28-byte index records record.go:64-84 in fixed
xxhash64-checksummed index pages, camelCase meta.json) — no reference
code involved. The importer must round-trip it into a native block
whose find-by-id and search answers are identical to writing the same
traces natively.
"""

import json
import struct

import pytest

from tempo_tpu import tempopb
from tempo_tpu.backend import LocalBackend
from tempo_tpu.db import TempoDB, TempoDBConfig
from tempo_tpu.db.importer import (
    ImportError_, dir_reader, import_reference_block,
)
from tempo_tpu.encoding.v2.compression import compress
from tempo_tpu.model.matches import trace_range_ns
from tempo_tpu.search.data import extract_search_data
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.utils.test_data import make_trace

_U32 = struct.Struct("<I")
_U16 = struct.Struct("<H")
_U64 = struct.Struct("<Q")


def write_ref_block(path, traces, encoding=None, data_encoding="v2",
                    objects_per_page=3, index_page_size=128):
    """traces: [(tid16, tempopb.Trace)] — written in sorted-id order,
    exactly as the reference appender does. encoding None = zstd when
    the codec exists on this host, else zlib (most tests here exercise
    the import machinery, not the codec; the parametrized roundtrip
    pins codecs explicitly and skips the unusable ones)."""
    from tempo_tpu.encoding.v2.compression import best_available

    if encoding is None:
        encoding = best_available("zstd")
    path.mkdir(parents=True, exist_ok=True)
    traces = sorted(traces, key=lambda t: t[0])

    def frame_object(tid, trace):
        s_ns, e_ns = trace_range_ns(trace)
        body = trace.SerializeToString()
        if data_encoding == "v2":
            body = struct.pack("<II", (s_ns // 10**9) & 0xFFFFFFFF,
                               (e_ns // 10**9) & 0xFFFFFFFF) + body
        return (_U32.pack(len(body) + len(tid) + 8) + _U32.pack(len(tid))
                + tid + body)

    data = bytearray()
    records = []
    for i in range(0, len(traces), objects_per_page):
        page_traces = traces[i:i + objects_per_page]
        raw = b"".join(frame_object(t, tr) for t, tr in page_traces)
        comp = compress(raw, encoding)
        page = _U32.pack(len(comp) + 6) + _U16.pack(0) + comp
        records.append((page_traces[-1][0], len(data), len(page)))
        data += page

    # index pages exactly as index_writer.go emits them: totalLen = the
    # FULL fixed page size, checksum over the whole padded data area,
    # records positional from the page start
    import xxhash

    index = bytearray()
    rec_per_page = (index_page_size - 14) // 28
    assert rec_per_page >= 1
    for i in range(0, len(records), rec_per_page):
        chunk = records[i:i + rec_per_page]
        recs = b"".join(struct.pack("<16sQI", rid, off, ln)
                        for rid, off, ln in chunk)
        area = recs + b"\x00" * (index_page_size - 14 - len(recs))
        page = (_U32.pack(index_page_size) + _U16.pack(8)
                + _U64.pack(xxhash.xxh64_intdigest(area)) + area)
        assert len(page) == index_page_size
        index += page

    (path / "data").write_bytes(bytes(data))
    (path / "index").write_bytes(bytes(index))
    (path / "meta.json").write_text(json.dumps({
        "format": "v2",
        "blockID": "11111111-2222-3333-4444-555555555555",
        "tenantID": "ref",
        "totalObjects": len(traces),
        "encoding": encoding,
        "indexPageSize": index_page_size,
        "totalRecords": len(records),
        "dataEncoding": data_encoding,
        "bloomShards": 1,
    }))


def _mk_db(tmp_path, name):
    be = LocalBackend(str(tmp_path / f"{name}-backend"))
    return TempoDB(be, str(tmp_path / f"{name}-wal"),
                   TempoDBConfig(host_state_dir=""))


@pytest.mark.parametrize("encoding", ["zstd", "gzip", "none"])
@pytest.mark.parametrize("data_encoding", ["v2", "v1"])
def test_roundtrip_find_and_search(tmp_path, encoding, data_encoding):
    from tempo_tpu.encoding.v2.compression import encoding_usable

    if not encoding_usable(encoding):
        pytest.skip(f"{encoding} codec unavailable on this host")
    traces = [(random_trace_id(), make_trace(b"", seed=i)) for i in range(7)]
    traces = [(tid, make_trace(tid, seed=i))
              for i, (tid, _) in enumerate(traces)]
    src = tmp_path / "refblock"
    write_ref_block(src, traces, encoding=encoding,
                    data_encoding=data_encoding)

    db = _mk_db(tmp_path, "imp")
    meta = import_reference_block(dir_reader(str(src)), db, "t1")
    assert meta.total_objects == 7

    # native twin: same traces written natively — answers must match
    ref = _mk_db(tmp_path, "nat")
    objs = []
    entries = []
    for tid, tr in sorted(traces, key=lambda t: t[0]):
        s_ns, e_ns = trace_range_ns(tr)
        from tempo_tpu.model.codec import segment_codec_for
        seg = segment_codec_for("v2").prepare_for_write(
            tr, s_ns // 10**9, e_ns // 10**9)
        objs.append((tid, seg, s_ns // 10**9, e_ns // 10**9))
        entries.append(extract_search_data(tid, tr))
    ref.write_block_direct("t1", objs, search_entries=entries)

    from tempo_tpu.model.codec import codec_for
    for tid, tr in traces:
        got, gf = db.find_trace_by_id("t1", tid)
        want, wf = ref.find_trace_by_id("t1", tid)
        assert got is not None and want is not None and gf == wf == 0
        g = codec_for("v2").prepare_for_read(got)
        w = codec_for("v2").prepare_for_read(want)
        assert g.SerializeToString() == w.SerializeToString(), tid.hex()

    for tags in ({}, {"service.name": "front"}, {"http.status_code": "500"}):
        req = tempopb.SearchRequest()
        for k, v in tags.items():
            req.tags[k] = v
        req.limit = 100
        got = {m.trace_id for m in db.search("t1", req).response().traces}
        want = {m.trace_id for m in ref.search("t1", req).response().traces}
        assert got == want, tags


def test_index_checksum_detects_corruption(tmp_path):
    traces = [(random_trace_id(), make_trace(random_trace_id(), seed=1))]
    src = tmp_path / "refblock"
    write_ref_block(src, traces)
    raw = bytearray((src / "index").read_bytes())
    raw[20] ^= 0xFF  # flip a record byte under the checksum
    (src / "index").write_bytes(bytes(raw))
    db = _mk_db(tmp_path, "imp")
    with pytest.raises(ImportError_, match="checksum"):
        import_reference_block(dir_reader(str(src)), db, "t1")


def test_torn_object_is_clean_error(tmp_path):
    traces = [(random_trace_id(), make_trace(random_trace_id(), seed=2))]
    src = tmp_path / "refblock"
    write_ref_block(src, traces, encoding="none")
    raw = bytearray((src / "data").read_bytes())
    # inflate the first object's declared length past the page
    struct.pack_into("<I", raw, 6, 1 << 30)
    (src / "data").write_bytes(bytes(raw))
    db = _mk_db(tmp_path, "imp")
    with pytest.raises(ImportError_):
        import_reference_block(dir_reader(str(src)), db, "t1")


def test_cli_import_ref(tmp_path):
    from tempo_tpu.cli import blocks as cli

    traces = [(random_trace_id(), make_trace(random_trace_id(), seed=i))
              for i in range(3)]
    traces = [(tid, make_trace(tid, seed=i))
              for i, (tid, _) in enumerate(traces)]
    src = tmp_path / "refblock"
    write_ref_block(src, traces)
    rc = cli.main(["--backend-path", str(tmp_path / "be"),
                   "import-ref", "t1", str(src)])
    assert rc == 0
    db = TempoDB(LocalBackend(str(tmp_path / "be")),
                 str(tmp_path / "wal"), TempoDBConfig(host_state_dir=""))
    db.poll()
    tid = traces[0][0]
    obj, failed = db.find_trace_by_id("t1", tid)
    assert obj is not None and failed == 0


def test_reference_default_index_page_size(tmp_path):
    """code-review r5: the reference's default indexPageSize is 250 KiB
    (256000), where (pageSize-14) % 28 != 0 — positional record parsing
    with checksummed padding must handle it (a record-aligned reading of
    totalLen broke on every real Go-written block)."""
    traces = [(random_trace_id(), None) for _ in range(5)]
    traces = [(tid, make_trace(tid, seed=i))
              for i, (tid, _) in enumerate(traces)]
    src = tmp_path / "refblock"
    write_ref_block(src, traces, objects_per_page=2,
                    index_page_size=256000)
    db = _mk_db(tmp_path, "imp")
    meta = import_reference_block(dir_reader(str(src)), db, "t1")
    assert meta.total_objects == 5
    tid = traces[0][0]
    obj, failed = db.find_trace_by_id("t1", tid)
    assert obj is not None and failed == 0


def test_partial_import_refused(tmp_path):
    """code-review r5: totalObjects disagreement (index missing pages)
    must error, never succeed with silently-missing traces."""
    traces = [(random_trace_id(), None) for _ in range(4)]
    traces = [(tid, make_trace(tid, seed=i))
              for i, (tid, _) in enumerate(traces)]
    src = tmp_path / "refblock"
    write_ref_block(src, traces, objects_per_page=2)
    meta = json.loads((src / "meta.json").read_text())
    meta["totalObjects"] = 9  # claims more than the index covers
    (src / "meta.json").write_text(json.dumps(meta))
    db = _mk_db(tmp_path, "imp")
    with pytest.raises(ImportError_, match="partial"):
        import_reference_block(dir_reader(str(src)), db, "t1")


def test_unsupported_encoding_fails_fast(tmp_path):
    """code-review r5: golang-framed codecs (lz4-*, snappy, s2) must be
    rejected up-front with the re-encode remedy, not fail mid-block."""
    traces = [(random_trace_id(), None)]
    traces = [(tid, make_trace(tid, seed=0)) for tid, _ in traces]
    src = tmp_path / "refblock"
    write_ref_block(src, traces)
    meta = json.loads((src / "meta.json").read_text())
    for enc in ("lz4-1M", "lz4", "snappy", "s2"):
        meta["encoding"] = enc
        (src / "meta.json").write_text(json.dumps(meta))
        db = _mk_db(tmp_path, f"imp-{enc}")
        with pytest.raises(ImportError_, match="re-encode"):
            import_reference_block(dir_reader(str(src)), db, "t1")
