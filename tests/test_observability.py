"""Observability: dispatch profiler, OpenMetrics exemplars, registry
thread-safety, self-trace health counters, and the metrics-catalog
drift guard.

The tentpole contracts pinned here:
  - every device dispatch mode (single / batched / coalesced / mesh /
    dict_probe) lands a stage breakdown in the profiler + histogram
  - `search_profiling_enabled: false` is a TRUE noop (shared immutable
    record, no clock reads)
  - exemplars appear in OpenMetrics output only under a sampled
    self-trace span, and parse per the OpenMetrics 1.0 text format
  - the docs metrics catalog cannot silently drift from the code
"""

from __future__ import annotations

import os
import random
import re
import threading
import time

import numpy as np
import pytest

from tempo_tpu import tempopb
from tempo_tpu.observability import metrics as obs
from tempo_tpu.observability import profile, tracing
from tempo_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
)
from tempo_tpu.search import ColumnarPages, PageGeometry
from tempo_tpu.search.data import SearchData


# ---------------------------------------------------------------- helpers


def _corpus(n=120, seed=0):
    rng = random.Random(seed)
    entries = []
    for i in range(n):
        tid = (seed.to_bytes(2, "big") + i.to_bytes(4, "big")).rjust(16, b"\x00")
        sd = SearchData(trace_id=tid)
        sd.start_s = 1_600_000_000 + seed * 1_000_000 + i
        sd.end_s = sd.start_s + 5
        sd.dur_ms = rng.randint(1, 30_000)
        sd.root_service = f"svc-{rng.randrange(4)}"
        sd.root_name = "GET /"
        sd.kvs = {
            "service.name": {sd.root_service},
            "http.status_code": {str(rng.choice([200, 404, 500]))},
        }
        entries.append(sd)
    return entries


def _mk_req(tags=None, **kw):
    req = tempopb.SearchRequest()
    for k, v in (tags or {}).items():
        req.tags[k] = v
    for k, v in kw.items():
        setattr(req, k, v)
    return req


@pytest.fixture
def sync_tracer():
    """Install an always-sampling tracer with an inline exporter;
    restore the no-tracer state afterwards."""
    exporter = tracing.CollectExporter()
    tracer = tracing.Tracer(tracing.SyncProcessor(exporter),
                            sample_ratio=1.0)
    tracing.set_tracer(tracer)
    yield tracer, exporter
    tracing.set_tracer(None)


@pytest.fixture
def profiler_reset():
    """Fresh profiler state around a test, enabled, fence off."""
    profile.configure(enabled=True, fence=False)
    profile.PROFILER.reset()
    yield profile.PROFILER
    profile.configure(enabled=True, fence=False)
    profile.PROFILER.reset()


# -------------------------------------------------- registry thread-safety


def test_counter_gauge_value_reads_are_consistent():
    reg = Registry()
    c = Counter("t_total", "t", registry=reg)
    g = Gauge("t_g", "t", registry=reg)
    c.inc(2, tenant="a")
    g.set(7.5, tenant="a")
    assert c.value(tenant="a") == 2
    assert c.value(tenant="missing") == 0
    assert g.value(tenant="a") == 7.5


def test_registry_concurrent_inc_observe_expose_stress():
    """Writers on every metric kind race a reader calling expose() in
    both formats; totals must come out exact and no expose may raise
    (the satellite fix: value()/expose() take the series lock)."""
    reg = Registry()
    c = Counter("s_total", "stress counter", registry=reg)
    g = Gauge("s_gauge", "stress gauge", registry=reg)
    h = Histogram("s_hist", "stress histogram", registry=reg)
    N_THREADS, N_OPS = 8, 400
    stop = threading.Event()
    errors = []

    def writer(tid):
        try:
            for i in range(N_OPS):
                c.inc(shard=str(tid % 4))
                g.set(i, shard=str(tid % 4))
                h.observe(i / N_OPS, shard=str(tid % 4))
                c.value(shard=str(tid % 4))
        except Exception as e:  # noqa: BLE001 — collected for the assert
            errors.append(e)

    def reader():
        try:
            while not stop.is_set():
                reg.expose()
                reg.expose(openmetrics=True)
                reg.samples()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(N_THREADS)]
    r = threading.Thread(target=reader)
    r.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    r.join()
    assert not errors
    total = sum(c.value(shard=str(s)) for s in range(4))
    assert total == N_THREADS * N_OPS
    # histogram observation counts add up exactly too
    assert sum(
        int(line.rsplit(" ", 1)[1])
        for line in reg.expose().splitlines()
        if line.startswith("s_hist_count")
    ) == N_THREADS * N_OPS


# ------------------------------------------------------ exemplars / formats

# OpenMetrics 1.0 exemplar on a bucket line:
#   name_bucket{labels} <int> # {trace_id="<hex>"} <value> <timestamp>
_EXEMPLAR_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)_bucket\{(?P<labels>[^}]*)\} '
    r'(?P<count>\d+) # \{trace_id="(?P<tid>[0-9a-f]{32})"\} '
    r'(?P<value>[0-9.eE+-]+) (?P<ts>[0-9]+(\.[0-9]+)?)$')


def test_histogram_exemplar_roundtrip_under_sampled_span(sync_tracer):
    tracer, _ = sync_tracer
    reg = Registry()
    h = Histogram("q_seconds", "q", registry=reg, buckets=(0.1, 1, 10))
    with tracer.start_span("scan") as span:
        h.observe(0.5, op="search")
        want_tid = span.context.trace_id.hex()

    om = reg.expose(openmetrics=True)
    assert om.endswith("# EOF\n")
    hits = [m for m in (
        _EXEMPLAR_RE.match(line) for line in om.splitlines()) if m]
    assert hits, f"no exemplar parsed from:\n{om}"
    m = hits[0]
    assert m.group("tid") == want_tid
    assert float(m.group("value")) == 0.5
    # the exemplar sits on the first bucket the value fell in (le=1)
    assert 'le="1.0"' in m.group("labels")
    # classic format stays exemplar-free and byte-compatible
    classic = reg.expose()
    assert "#" not in classic.replace("# HELP", "").replace("# TYPE", "")
    assert 'le="1"' in classic


def test_exemplar_absent_without_span_or_when_sampled_out():
    reg = Registry()
    h = Histogram("nospan_seconds", "q", registry=reg, buckets=(1,))
    h.observe(0.5)  # no tracer at all
    assert " # {" not in reg.expose(openmetrics=True)

    exporter = tracing.CollectExporter()
    tracer = tracing.Tracer(tracing.SyncProcessor(exporter),
                            sample_ratio=0.0)  # everything sampled OUT
    tracing.set_tracer(tracer)
    try:
        with tracer.start_span("scan"):
            h.observe(0.7)
    finally:
        tracing.set_tracer(None)
    assert " # {" not in reg.expose(openmetrics=True)


def test_openmetrics_counter_family_naming():
    """OpenMetrics names counter FAMILIES without the _total suffix in
    HELP/TYPE; the sample line keeps it. Classic format is unchanged."""
    reg = Registry()
    c = Counter("things_done_total", "things", registry=reg)
    c.inc(3)
    om = reg.expose(openmetrics=True)
    assert "# TYPE things_done counter" in om
    assert "things_done_total 3" in om
    classic = reg.expose()
    assert "# TYPE things_done_total counter" in classic


# ------------------------------------------------- self-trace health fixes


def test_selftrace_dropped_spans_counter(sync_tracer):
    tracer, _ = sync_tracer

    class _NeverExporter:
        def export(self, spans):
            pass

    bp = tracing.BatchProcessor(_NeverExporter(), max_queue=2,
                                interval_s=3600)
    try:
        # the metric is labelled by exporter class and is the single
        # source of truth — bp.dropped reads it back, no shadow count
        before = obs.selftrace_dropped_spans.value(
            exporter="_NeverExporter")
        for _ in range(5):
            with tracer.start_span("s") as sp:
                pass
            bp.on_end(sp)
        assert bp.dropped >= 3
        assert (obs.selftrace_dropped_spans.value(exporter="_NeverExporter")
                - before == bp.dropped)
    finally:
        bp.shutdown()


def test_selftrace_export_failure_counter(sync_tracer):
    tracer, _ = sync_tracer

    class _BoomExporter:
        def export(self, spans):
            raise RuntimeError("collector is down")

    bp = tracing.BatchProcessor(_BoomExporter(), interval_s=3600)
    try:
        before = obs.selftrace_export_failures.value(
            exporter="_BoomExporter")
        with tracer.start_span("s") as sp:
            pass
        bp.on_end(sp)
        bp.force_flush()  # swallows the raise, but must COUNT it
        assert obs.selftrace_export_failures.value(
            exporter="_BoomExporter") - before == 1
    finally:
        bp.shutdown()


# ---------------------------------------------------------- profiler core


def test_profiler_noop_path_is_shared_and_cheap(profiler_reset):
    prof = profiler_reset
    profile.configure(enabled=False)
    rec = profile.dispatch("single")
    assert rec is profile.NOOP_DISPATCH
    assert profile.dispatch("mesh") is rec  # shared, not allocated
    # the full call-site protocol is inert
    with rec:
        with rec.stage("build"):
            pass
        assert rec.compile_check(("k",)) is False
        rec.add_bytes(h2d=10).add_stage("h2d", 1.0).set(x=1).fence([])
    assert prof.snapshot()["dispatches"] == 0
    assert not prof.snapshot()["aggregates"]
    # observe_stage is a noop too
    profile.observe_stage("h2d", "single", 1.0, nbytes=1 << 30)
    assert prof.snapshot()["bytes"]["h2d"] == 0

    # overhead micro-check: 100k full noop call-sequences in well under
    # a second — the "true noop" contract at test granularity (bench.py
    # phase profile_overhead holds the <2% end-to-end line)
    t0 = time.perf_counter()
    for _ in range(100_000):
        r = profile.dispatch("single")
        with r.stage("build"):
            pass
        r.close()
    assert time.perf_counter() - t0 < 1.0


def test_profiler_record_aggregation_and_ring(profiler_reset):
    prof = profiler_reset
    # the stage histogram is process-global: assert deltas, not totals
    om = obs.dispatch_stage_seconds
    key = om._key({"stage": "execute", "mode": "batched"})
    with om._lock:
        before = sum(om._counts.get(key, [0]))
    with profile.dispatch("batched") as rec:
        rec.add_stage("build", 0.002)
        with rec.stage("execute"):
            time.sleep(0.001)
        rec.add_bytes(h2d=100, d2h=50)
        assert rec.compile_check(("shape", 1)) is True   # first sight
    with profile.dispatch("batched") as rec2:
        assert rec2.compile_check(("shape", 1)) is False  # cached
        rec2.add_stage("execute", 0.001)
    snap = prof.snapshot()
    assert snap["dispatches"] == 2
    assert snap["jit_cache"] == {"hit": 1, "miss": 1}
    assert snap["bytes"] == {"h2d": 100, "d2h": 50}
    agg = snap["aggregates"]["batched"]
    assert agg["build"]["count"] == 1
    assert agg["execute"]["count"] == 2
    assert agg["execute"]["total_ms"] >= 1.0
    recent = snap["recent"]
    assert len(recent) == 2
    assert recent[0]["jit_cache"] == "miss"
    assert recent[1]["jit_cache"] == "hit"
    assert recent[0]["stages_ms"]["build"] == pytest.approx(2.0, abs=0.5)
    # metrics side: the stage histogram saw both dispatches
    with om._lock:
        assert sum(om._counts[key]) - before == 2

    prof.reset()
    assert prof.snapshot()["dispatches"] == 0


def test_profiler_stage_events_annotate_span(sync_tracer, profiler_reset):
    tracer, _ = sync_tracer
    with tracer.start_span("query") as span:
        with profile.dispatch("single") as rec:
            rec.add_stage("execute", 0.003)
        profile.observe_stage("d2h", "single", 0.001)
    names = [name for _ts, name, _attrs in span.events]
    assert "dispatch.profile" in names
    assert "profile.stage" in names


def test_profiler_ring_resize_and_bound(profiler_reset):
    prof = profiler_reset
    profile.configure(ring_size=4)
    try:
        for i in range(10):
            with profile.dispatch("single") as rec:
                rec.add_stage("build", 0.001 * (i + 1))
        assert len(prof.snapshot(recent=100)["recent"]) == 4
    finally:
        profile.configure(ring_size=256)


def test_fence_arrays_tolerates_host_values():
    profile.fence_arrays((1, None, np.zeros(2)))  # must not raise


# --------------------------------------- every dispatch mode is profiled


def _modes_seen():
    return set(profile.PROFILER.snapshot()["aggregates"])


def test_all_dispatch_modes_populate_profiler(profiler_reset):
    """Acceptance: /debug/profile and the stage histogram populated for
    single, batched, coalesced, mesh AND dict_probe dispatches."""
    from tempo_tpu.parallel import make_mesh
    from tempo_tpu.search import dict_probe
    from tempo_tpu.search.engine import ScanEngine, stage
    from tempo_tpu.search.multiblock import (
        MultiBlockEngine,
        compile_multi,
        stack_queries,
    )
    from tempo_tpu.search.pipeline import compile_query

    req = _mk_req({"service.name": "svc-1"}, limit=20)
    blocks = [ColumnarPages.build(_corpus(100, seed=s), PageGeometry(16, 8))
              for s in range(3)]

    # single
    eng = ScanEngine(top_k=64)
    cq = compile_query(blocks[0].key_dict, blocks[0].val_dict, req)
    eng.scan_staged(stage(blocks[0]), cq)
    assert "single" in _modes_seen()

    # batched (multi-block, one device)
    mbe = MultiBlockEngine(top_k=64)
    batch = mbe.stage(blocks)
    mq = compile_multi(blocks, req)
    mbe.scan(batch, mq)
    assert "batched" in _modes_seen()

    # coalesced (two stacked queries, one fused kernel)
    mq2 = compile_multi(blocks, _mk_req({"service.name": "svc-2"},
                                        limit=20))
    ccq = stack_queries([mq, mq2])
    out = mbe.coalesced_scan_async(batch, ccq, 64)
    from tempo_tpu.search.engine import fetch_coalesced_out

    fetch_coalesced_out(out)
    assert "coalesced" in _modes_seen()

    # mesh (8 virtual CPU devices, conftest)
    dist = MultiBlockEngine(top_k=64, mesh=make_mesh())
    dist.scan(dist.stage(blocks), mq)
    assert "mesh" in _modes_seen()

    # dict_probe kernel
    ddev = dict_probe.place_device_dict(
        dict_probe.pack_device_dict(blocks[0].val_dict))
    dict_probe.probe_value_hits(ddev, [b"svc-1"])
    assert "dict_probe" in _modes_seen()

    snap = profile.PROFILER.snapshot()
    for mode in ("single", "batched", "coalesced", "mesh", "dict_probe"):
        stages = snap["aggregates"][mode]
        assert stages, f"mode {mode} has no stage aggregates"
        # every profiled dispatch timed its kernel call
        assert "compile" in stages or "execute" in stages or \
            "h2d" in stages
    # the histogram carries the same series
    exposed = obs.dispatch_stage_seconds.expose()
    for mode in ("single", "batched", "coalesced", "mesh", "dict_probe"):
        assert f'mode="{mode}"' in exposed
    # jit-cache events observed for the fresh shapes
    assert snap["jit_cache"]["miss"] >= 4


def test_host_probe_mode_recorded(profiler_reset):
    """The host memmem prefilter (PR4's motivating cost) records under
    mode=host_probe so the stage histogram shows host vs device probe."""
    from tempo_tpu.search.pipeline import compile_query

    block = ColumnarPages.build(_corpus(60, seed=1), PageGeometry(16, 8))
    compile_query(block.key_dict, block.val_dict,
                  _mk_req({"service.name": "svc-1"}, limit=20))
    agg = profile.PROFILER.snapshot()["aggregates"]
    assert "host_probe" in agg
    assert agg["host_probe"]["build"]["count"] >= 1


def test_profiler_disabled_leaves_dispatch_paths_silent(profiler_reset):
    from tempo_tpu.search.engine import ScanEngine, stage
    from tempo_tpu.search.pipeline import compile_query

    profile.configure(enabled=False)
    block = ColumnarPages.build(_corpus(80, seed=2), PageGeometry(16, 8))
    eng = ScanEngine(top_k=64)
    cq = compile_query(block.key_dict, block.val_dict,
                       _mk_req({"service.name": "svc-1"}, limit=20))
    eng.scan_staged(stage(block), cq)
    snap = profile.PROFILER.snapshot()
    assert snap["dispatches"] == 0
    assert not snap["aggregates"]


# ------------------------------------------------------ catalog drift guard


def test_metrics_catalog_complete():
    """Every metric name registered anywhere in tempo_tpu/ must appear
    in docs/observability.md — the catalog cannot silently drift.
    Thin wrapper over the analysis drift engine's "metric-names"
    catalog (tempo_tpu/analysis/drift.py; same invariant this test
    enforced with a hand-rolled regex walk before PR 10, incl. the
    >=30-names extractor sanity floor)."""
    from tempo_tpu.analysis.drift import catalog_findings

    findings = catalog_findings("metric-names")
    assert not findings, (
        "metrics missing from docs/observability.md catalog "
        "(add them to the table):\n"
        + "\n".join(f"{f.path}:{f.line}: {f.message}" for f in findings))
