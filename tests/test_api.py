import json
import threading
import urllib.request

import grpc
import pytest

from tempo_tpu import tempopb
from tempo_tpu.api import (
    HTTPApi,
    PusherClient,
    QuerierClient,
    build_search_request,
    make_grpc_server,
    parse_search_request,
    serve_http,
)
from tempo_tpu.api.grpc_service import OTLP_EXPORT_METHOD
from tempo_tpu.cli.config import load_config, expand_env
from tempo_tpu.modules import App, AppConfig
from tempo_tpu.utils.ids import random_trace_id, trace_id_to_hex
from tempo_tpu.utils.test_data import make_trace

from tests.test_search import _mk_req


@pytest.fixture
def app(tmp_path):
    return App(AppConfig(wal_dir=str(tmp_path / "wal")))


def test_search_request_param_roundtrip():
    req = _mk_req({"service.name": "front end", "x": "1"},
                  min_duration_ms=1500, limit=30, start=100, end=200)
    qs = build_search_request(req)
    parsed = parse_search_request(
        {k: v[0] for k, v in
         __import__("urllib.parse", fromlist=["parse_qs"]).parse_qs(qs).items()}
    )
    assert dict(parsed.tags) == {"service.name": "front", "x": "1"} or \
        dict(parsed.tags) == dict(req.tags)
    assert parsed.min_duration_ms == 1500
    assert parsed.limit == 30 and parsed.start == 100 and parsed.end == 200


def test_http_api_routes(app):
    api = HTTPApi(app)
    tid = random_trace_id()
    tr = make_trace(tid, seed=1)
    app.push("t1", list(tr.batches))

    hdr = {"X-Scope-OrgID": "t1"}
    code, body = api.handle("GET", "/api/echo", {}, hdr)
    assert code == 200 and body == "echo"
    code, _ = api.handle("GET", "/ready", {}, hdr)
    assert code == 200

    code, body = api.handle("GET", f"/api/traces/{trace_id_to_hex(tid)}", {}, hdr)
    assert code == 200
    assert len(body["batches"]) == len(tr.batches)

    # wrong tenant → 404
    code, _ = api.handle("GET", f"/api/traces/{trace_id_to_hex(tid)}", {},
                         {"X-Scope-OrgID": "other"})
    assert code == 404

    code, body = api.handle("GET", "/api/search", {"tags": "component=db",
                                                   "limit": "10"}, hdr)
    assert code == 200 and "traces" in body or body == {}

    code, body = api.handle("GET", "/api/search/tags", {}, hdr)
    assert code == 200 and "component" in body.get("tagNames", [])

    code, body = api.handle("GET", "/api/search/tag/component/values", {}, hdr)
    assert code == 200 and body.get("tagValues")

    code, body = api.handle("GET", "/status", {}, hdr)
    assert code == 200 and body["ready"] is True

    code, body = api.handle("GET", "/metrics", {}, hdr)
    assert code == 200

    # malformed trace id → 400
    code, _ = api.handle("GET", "/api/traces/zzzz", {}, hdr)
    assert code == 400


def test_http_server_end_to_end(app):
    api = HTTPApi(app)
    server = serve_http(api, host="127.0.0.1", port=0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        tid = random_trace_id()
        app.push("t1", list(make_trace(tid, seed=2).batches))
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/traces/{trace_id_to_hex(tid)}",
            headers={"X-Scope-OrgID": "t1"},
        )
        with urllib.request.urlopen(req) as r:
            body = json.loads(r.read())
            assert r.status == 200 and body["batches"]
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/api/echo") as r:
            assert r.read() == b"echo"
    finally:
        server.shutdown()


def test_grpc_services_and_otlp_export(app):
    server = make_grpc_server(app, "127.0.0.1:0")
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        addr = f"127.0.0.1:{port}"
        tid = random_trace_id()
        tr = make_trace(tid, seed=3)

        # OTLP export: raw wire-compatible Export call
        channel = grpc.insecure_channel(addr)
        rpc = channel.unary_unary(
            OTLP_EXPORT_METHOD,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=tempopb.Trace.FromString,
        )
        rpc(tr, metadata=(("x-scope-orgid", "t1"),))

        # query it back over the Querier service
        qc = QuerierClient(addr)
        resp = qc.find_trace_by_id("t1", tid)
        assert len(resp.trace.batches) == len(tr.batches)

        sreq = _mk_req({})
        sreq.limit = 10
        sresp = qc.search_recent("t1", sreq)
        assert len(sresp.traces) == 1

        tags = qc.search_tags("t1")
        assert "service.name" in tags.tag_names

        # Pusher service: push pre-marshalled segments
        pc = PusherClient(addr)
        from tempo_tpu.model.codec import segment_codec_for
        from tempo_tpu.search.data import extract_search_data, encode_search_data

        tid2 = random_trace_id()
        tr2 = make_trace(tid2, seed=4)
        sd = extract_search_data(tid2, tr2)
        push = tempopb.PushBytesRequest()
        push.ids.append(tid2)
        push.traces.append(segment_codec_for("v2").prepare_for_write(tr2, 1, 2))
        push.search_data.append(encode_search_data(sd))
        pc.push_bytes("t1", push)
        resp2 = qc.find_trace_by_id("t1", tid2)
        assert len(resp2.trace.batches) == len(tr2.batches)
    finally:
        server.stop(grace=None)


def test_config_load_and_env_expand(tmp_path, monkeypatch):
    monkeypatch.setenv("BLOCK_PATH", "/data/blocks")
    text = """
server: {http_port: 3201}
storage:
  backend: local
  local: {path: ${BLOCK_PATH}}
  wal_dir: ${WAL_DIR:/data/wal}
ingester: {n_ingesters: 2, replication_factor: 3}
overrides:
  defaults: {max_live_traces: 123}
  per_tenant:
    vip: {max_live_traces: 999}
"""
    cfg, runtime = load_config(text=text)
    assert cfg.backend["local"]["path"] == "/data/blocks"
    assert cfg.wal_dir == "/data/wal"
    assert cfg.limits.max_live_traces == 123
    assert cfg.per_tenant_overrides["vip"]["max_live_traces"] == 999
    assert runtime["http_port"] == 3201
    # footgun warning: rf > ingesters
    assert any("replication_factor" in w for w in runtime["warnings"])


def test_metrics_registry():
    from tempo_tpu.observability.metrics import Registry, Counter, Histogram

    reg = Registry()
    c = Counter("test_total", "help", registry=reg)
    c.inc(tenant="a")
    c.inc(2, tenant="a")
    c.inc(tenant="b")
    assert c.value(tenant="a") == 3
    h = Histogram("test_seconds", "help", registry=reg)
    h.observe(0.3)
    out = reg.expose()
    assert 'test_total{tenant="a"} 3' in out
    assert "test_seconds_bucket" in out and "test_seconds_count 1" in out


def test_http_ingest_edge_cases(app):
    """Chunked-transfer ingest must not be silently dropped; malformed
    Zipkin arrays must map to 400 (client error), not 500."""
    import http.client

    api = HTTPApi(app)
    server = serve_http(api, host="127.0.0.1", port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        # chunked OTLP/HTTP push
        tid = random_trace_id()
        payload = make_trace(tid, seed=9).SerializeToString()
        conn = http.client.HTTPConnection("127.0.0.1", port)
        conn.putrequest("POST", "/v1/traces")
        conn.putheader("Transfer-Encoding", "chunked")
        conn.putheader("X-Scope-OrgID", "t1")
        conn.endheaders()
        for i in range(0, len(payload), 100):
            chunk = payload[i:i + 100]
            conn.send(f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
        conn.send(b"0\r\n\r\n")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200 and body["accepted_batches"] > 0

        # zipkin array of non-objects → 400, not 500
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/v2/spans", data=b'["oops", 1]',
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 400
    finally:
        server.shutdown()


def test_distributor_rejects_bad_quorum_mode():
    from tempo_tpu.modules.distributor import Distributor
    from tempo_tpu.modules.ring import Ring

    with pytest.raises(ValueError):
        Distributor(Ring(["i0"]), {}, write_quorum="One")


def test_http_body_limits(app):
    """Oversize Content-Length → 413 (never truncate-and-accept); negative
    chunk size → 400."""
    import http.client

    api = HTTPApi(app)
    server = serve_http(api, host="127.0.0.1", port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port)
        conn.putrequest("POST", "/v1/traces")
        conn.putheader("Content-Length", str(100 << 20))
        conn.endheaders()
        resp = conn.getresponse()
        assert resp.status == 413
        conn.close()

        conn = http.client.HTTPConnection("127.0.0.1", port)
        conn.putrequest("POST", "/v1/traces")
        conn.putheader("Transfer-Encoding", "chunked")
        conn.endheaders()
        conn.send(b"-1\r\n")
        resp = conn.getresponse()
        assert resp.status == 400
        conn.close()
    finally:
        server.shutdown()


def test_status_config_modes(app):
    api = HTTPApi(app)
    code, full = api.handle("GET", "/status/config", {}, {})
    assert code == 200 and full["wal_dir"] == app.cfg.wal_dir
    code, defaults = api.handle("GET", "/status/config", {"mode": "defaults"}, {})
    assert code == 200 and defaults["wal_dir"] == "./wal"
    code, diff = api.handle("GET", "/status/config", {"mode": "diff"}, {})
    assert code == 200
    # only the overridden keys appear in the diff
    assert diff["wal_dir"] == app.cfg.wal_dir
    assert "replication_factor" not in diff


def test_exhaustive_debug_tag(app):
    """Hidden debug flag (reference SecretExhaustiveSearchTag): forces a
    FULL traversal — no block pruning, no early quit — while the other
    tag predicates still apply (the reference keeps them and suppresses
    early exit; round-1 had this inverted, see ADVICE r1)."""
    from tempo_tpu.search.pipeline import EXHAUSTIVE_SEARCH_TAG

    tids = [random_trace_id() for _ in range(5)]
    for i, tid in enumerate(tids):
        app.push("t1", list(make_trace(tid, seed=i).batches))
    app.flush_tick(force=True)
    app.poll_tick()

    # a key no block has: normally the whole tenant prunes with no scan
    narrow = _mk_req({"no.such.key": "x"})
    narrow.limit = 50
    resp = app.search("t1", narrow)
    assert len(resp.traces) == 0
    assert resp.metrics.inspected_traces == 0  # pruned, nothing scanned

    # with the debug flag the predicate still rejects everything, but the
    # scan is forced through every entry
    dbg = _mk_req({EXHAUSTIVE_SEARCH_TAG: "1", "no.such.key": "x"})
    dbg.limit = 50
    resp = app.search("t1", dbg)
    assert len(resp.traces) == 0
    assert resp.metrics.inspected_traces >= len(tids)  # full traversal

    # flag alone: full scan, everything matches, limit ignored for quitting
    dbg2 = _mk_req({EXHAUSTIVE_SEARCH_TAG: "1"})
    dbg2.limit = 2
    resp = app.search("t1", dbg2)
    assert resp.metrics.inspected_traces >= len(tids)
    assert len(resp.traces) == 2  # response still honors the limit


def test_status_config_redacts_secrets(tmp_path):
    app2 = App(AppConfig(
        wal_dir=str(tmp_path / "wal2"),
        backend={"backend": "memory",
                 "s3": {"bucket": "b", "secret_key": "sssh", "access_key": "ak"}},
        metrics_generator={"remote_write": {
            "url": "http://mim/push",
            "headers": {"Authorization": "Bearer tok"}}},
    ))
    api = HTTPApi(app2)
    _, full = api.handle("GET", "/status/config", {}, {})
    s3 = full["backend"]["s3"]
    assert s3["secret_key"] == "<redacted>" and s3["access_key"] == "<redacted>"
    assert s3["bucket"] == "b"
    rw = full["metrics_generator"]["remote_write"]
    assert rw["headers"] == "<redacted>" and rw["url"] == "http://mim/push"
    _, diff = api.handle("GET", "/status/config", {"mode": "diff"}, {})
    assert "sssh" not in str(diff) and "tok" not in str(diff)
    app2.shutdown()


def test_exhaustive_tag_multiblock():
    """The debug tag forces traversal through the multi-block engine too:
    a block that would prune (no dictionary value satisfies the term)
    still compiles and scans — the term just matches nothing — and the
    secret tag itself never becomes a predicate."""
    from tempo_tpu.search.multiblock import compile_multi
    from tempo_tpu.search.pipeline import EXHAUSTIVE_SEARCH_TAG

    from tempo_tpu.search.columnar import ColumnarPages
    from tempo_tpu.search.data import SearchData
    import os as _os

    entries = []
    for i in range(8):
        sd = SearchData(trace_id=_os.urandom(16))
        sd.start_s, sd.end_s, sd.dur_ms = 100 + i, 105 + i, 50
        sd.kvs = {"service.name": {"svc"}}
        entries.append(sd)
    pages = ColumnarPages.build(entries)

    # without the flag: unsatisfiable term prunes the whole block
    assert compile_multi([pages], _mk_req({"service.name": "nope"})) is None

    req = _mk_req({EXHAUSTIVE_SEARCH_TAG: "1", "service.name": "nope"})
    mq = compile_multi([pages], req)
    assert mq is not None and mq.n_terms == 1  # real predicate kept
    from tempo_tpu.search.multiblock import MultiBlockEngine, stack_blocks

    batch = stack_blocks([pages])
    count, inspected, _, _ = MultiBlockEngine().scan(batch, mq)
    assert inspected == 8  # forced full scan
    assert count == 0      # predicate still rejects

    # flag alone: zero terms, everything scanned and matched
    mq2 = compile_multi([pages], _mk_req({EXHAUSTIVE_SEARCH_TAG: "1"}))
    assert mq2 is not None and mq2.n_terms == 0
    count, inspected, _, _ = MultiBlockEngine().scan(batch, mq2)
    assert count == 8 == inspected


# ---------------------------------------------------------------------------
# debug endpoints (reference cmd/tempo/main.go:54-115 pprof role)


def test_debug_threads_dumps_all_stacks(app):
    api = HTTPApi(app)
    code, body = api.handle("GET", "/debug/threads", {}, {})
    assert code == 200
    assert "--- thread MainThread" in body
    assert "test_debug_threads_dumps_all_stacks" in body  # our own frame


def test_debug_endpoints_gated_off(app):
    """ADVICE r4: /debug/* exposes stacks and internals; deployments
    (cli/config.py server.debug_endpoints, default false) can turn the
    routes off — they answer 404, everything else still works."""
    api = HTTPApi(app, debug_endpoints=False)
    for p in ("/debug/threads", "/debug/scan", "/debug/profile",
              "/debug/querystats",
              "/debug/planner"):
        code, body = api.handle("GET", p, {}, {})
        assert code == 404, (p, code)
        assert "disabled" in body["error"]
    code, _ = api.handle("GET", "/ready", {}, {})
    assert code in (200, 503)


def test_debug_profile_endpoint(app):
    """/debug/profile: dispatch profiler snapshot (recent + aggregates),
    behind the same gate as the other /debug routes."""
    from tempo_tpu.observability import profile

    api = HTTPApi(app)
    profile.configure(enabled=True)
    profile.PROFILER.reset()
    try:
        with profile.dispatch("batched") as rec:
            rec.add_stage("execute", 0.004)
        code, body = api.handle("GET", "/debug/profile", {}, {})
        assert code == 200
        assert body["enabled"] is True
        assert body["dispatches"] == 1
        assert body["aggregates"]["batched"]["execute"]["count"] == 1
        assert body["recent"][0]["mode"] == "batched"
        # ?recent=0 truncates the ring listing, keeps aggregates
        code, body = api.handle("GET", "/debug/profile",
                                {"recent": "0"}, {})
        assert code == 200 and body["recent"] == []
    finally:
        profile.PROFILER.reset()


def test_metrics_content_type_negotiation(app):
    """/metrics answers the classic Prometheus type by default and the
    OpenMetrics type (with # EOF terminator) when the scraper Accepts
    it — the parser on the other end keys off Content-Type."""
    api = HTTPApi(app)
    code, body = api.handle("GET", "/metrics", {}, {})
    assert code == 200
    assert body.content_type == "text/plain; version=0.0.4"
    assert not body.rstrip().endswith("# EOF")

    code, om = api.handle(
        "GET", "/metrics", {},
        {"Accept": "application/openmetrics-text; version=1.0.0"})
    assert code == 200
    assert om.content_type.startswith("application/openmetrics-text")
    assert om.rstrip().endswith("# EOF")


def test_metrics_content_type_on_the_wire(app):
    """End-to-end through the stdlib server: the negotiated type reaches
    the HTTP response header."""
    import urllib.request

    api = HTTPApi(app)
    server = serve_http(api, host="127.0.0.1", port=0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        r = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics")
        assert r.headers["Content-Type"] == "text/plain; version=0.0.4"
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/metrics",
            headers={"Accept": "application/openmetrics-text"})
        r = urllib.request.urlopen(req)
        assert r.headers["Content-Type"].startswith(
            "application/openmetrics-text")
        assert r.read().rstrip().endswith(b"# EOF")
    finally:
        server.shutdown()


def test_debug_scan_reports_stage_breakdown(app):
    api = HTTPApi(app)
    tid = random_trace_id()
    app.push("t1", list(make_trace(tid, seed=11).batches))
    app.flush_tick(force=True)
    app.poll_tick()

    # before any scan: caches present, no last_scan yet
    code, body = api.handle("GET", "/debug/scan", {}, {})
    assert code == 200
    assert body["hbm_cache"]["budget_bytes"] > 0
    assert body["host_cache"]["budget_bytes"] > 0

    req = _mk_req({})
    req.limit = 10
    app.search("t1", req)
    code, body = api.handle("GET", "/debug/scan", {}, {})
    assert code == 200
    last = body["last_scan"]
    assert last is not None and last["scan_dispatches"] >= 1
    for stage in ("header_prune", "staging", "prepare", "dispatch", "drain"):
        assert stage in last["stages_ms"]
    assert last["total_ms"] > 0
    # the stages must account for a meaningful share of the total —
    # a breakdown that misses the time is worse than none
    assert sum(last["stages_ms"].values()) <= last["total_ms"] * 1.05


def test_config_maps_frontend_querier_and_serving_knobs():
    from tempo_tpu.cli.config import load_config

    cfg, _ = load_config(text="""
frontend:
  tolerate_failed_blocks: 3
  batch_jobs_per_request: 64
  grpc_max_workers: 300
querier:
  frontend_worker_parallelism: 4
storage:
  wal_encoding: zlib
  search_prewarm_on_poll: true
  search_batch_cache_bytes: 1073741824
""")
    assert cfg.frontend.tolerate_failed_blocks == 3
    assert cfg.frontend.batch_jobs_per_request == 64
    assert cfg.frontend_worker_parallelism == 4
    assert cfg.frontend_grpc_max_workers == 300
    assert cfg.db.wal_encoding == "zlib"
    assert cfg.db.search_prewarm_on_poll is True
    assert cfg.db.search_batch_cache_bytes == 1 << 30
    # defaults survive an empty doc (host cache auto-sizes at None)
    cfg2, _ = load_config(text="{}")
    assert cfg2.db.search_host_cache_bytes is None
    assert cfg2.frontend.batch_jobs_per_request is None


def test_http_garbage_query_params_are_client_errors(app):
    """Hostile/garbage query params must map to 400s (or safe defaults),
    never 500 — the parse layer's int()/duration errors are client
    errors."""
    api = HTTPApi(app)
    hdr = {"X-Scope-OrgID": "t1"}
    for path, query in [
        ("/api/search", {"limit": "not-a-number"}),
        ("/api/search", {"start": "1e99"}),
        ("/api/search", {"minDuration": "banana"}),
        ("/api/search", {"maxDuration": "-5ms"}),
        ("/api/traces/zzzz-not-hex", {}),
        ("/api/traces/" + "f" * 4096, {}),  # absurd length
        ("/api/search/tag//values", {}),
    ]:
        code, body = api.handle("GET", path, query, hdr)
        assert code in (400, 404), (path, query, code, body)


def test_tenant_path_traversal_rejected(tmp_path):
    """X-Scope-OrgID is attacker-controllable and flows into filesystem
    paths: traversal attempts must 400 at the API and raise at the
    backend, and nothing may be written outside the backend root."""
    import os

    from tempo_tpu.backend import LocalBackend

    app2 = App(AppConfig(
        backend={"backend": "local", "local": {"path": str(tmp_path / "be")}},
        wal_dir=str(tmp_path / "wal")))
    api = HTTPApi(app2, multitenancy=True)
    evil = ["../../../../tmp/evil", "..", "a/b", "a\\b", "t\x00x", "x" * 200]
    for tenant in evil:
        code, _ = api.handle(
            "POST", "/v1/traces",
            {}, {"X-Scope-OrgID": tenant},
            make_trace(random_trace_id(), seed=1).SerializeToString())
        assert code == 400, (tenant, code)
        code, _ = api.handle("GET", "/api/search", {"limit": "5"},
                             {"X-Scope-OrgID": tenant})
        assert code == 400, (tenant, code)
    # backend defense in depth
    be = LocalBackend(str(tmp_path / "be2"))
    import pytest as _pytest
    for tenant in ("../esc", "a/b", ".."):
        with _pytest.raises(ValueError):
            be.write(tenant, "blk", "meta.json", b"{}")
    assert not os.path.exists(str(tmp_path / "esc"))
    # normal tenants unaffected
    be.write("ok-tenant_1", "blk", "meta.json", b"{}")


def test_grpc_invalid_tenant_is_invalid_argument(tmp_path):
    """An invalid X-Scope-OrgID over gRPC must fail INVALID_ARGUMENT —
    UNKNOWN reads as retryable to standard OTLP exporters."""
    import socket

    import grpc

    from tempo_tpu.api.grpc_service import make_module_grpc_server

    class P:
        def push_bytes(self, tenant, req):
            pass

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    server = make_module_grpc_server(f"127.0.0.1:{port}", pusher=P())
    server.start()
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        rpc = ch.unary_unary(
            "/tempopb.Pusher/PushBytes",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=tempopb.PushResponse.FromString)
        with pytest.raises(grpc.RpcError) as ei:
            rpc(tempopb.PushBytesRequest(),
                metadata=(("x-scope-orgid", "../../etc"),))
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        ch.close()
    finally:
        server.stop(0)


def test_grpc_server_side_valueerror_is_internal(tmp_path):
    """ADVICE r4: a plain ValueError from the handler (corrupt WAL
    entry, object framing) is server-side — it must surface INTERNAL,
    not be reclassified as a non-retryable client INVALID_ARGUMENT."""
    import socket

    import grpc

    from tempo_tpu.api.grpc_service import make_module_grpc_server

    class P:
        def push_bytes(self, tenant, req):
            raise ValueError("corrupt wal entry at offset 42")

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    server = make_module_grpc_server(f"127.0.0.1:{port}", pusher=P())
    server.start()
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        rpc = ch.unary_unary(
            "/tempopb.Pusher/PushBytes",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=tempopb.PushResponse.FromString)
        with pytest.raises(grpc.RpcError) as ei:
            rpc(tempopb.PushBytesRequest(),
                metadata=(("x-scope-orgid", "fine-tenant"),))
        assert ei.value.code() == grpc.StatusCode.INTERNAL
        assert "corrupt wal entry" in ei.value.details()
        ch.close()
    finally:
        server.stop(0)


def test_live_traces_limit_is_429_not_500(tmp_path):
    """Soak finding r5: the ingester's max-live-traces pushback surfaced
    as HTTP 500 through the quorum error path. It is retryable tenant
    backpressure — the reference answers FailedPrecondition /
    ResourceExhausted (instance.go:185, distributor.go:305) → 429."""
    from tempo_tpu.modules import App, AppConfig
    from tempo_tpu.modules.overrides import Limits

    app2 = App(AppConfig(
        backend={"backend": "local", "local": {"path": str(tmp_path / "b")}},
        wal_dir=str(tmp_path / "w")))
    app2.overrides.defaults = Limits(max_live_traces=3)
    api = HTTPApi(app2)
    hdr = {"X-Scope-OrgID": "t1"}
    codes = []
    for i in range(6):
        tr = make_trace(random_trace_id(), seed=i)
        code, body = api.handle("POST", "/v1/traces", {}, hdr,
                                tr.SerializeToString())
        codes.append(code)
    assert 429 in codes and 500 not in codes, codes
    assert codes[0] == 200
