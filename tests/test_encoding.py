import os
import random

import pytest

from tempo_tpu.backend import BlockMeta, LocalBackend, MockBackend, DoesNotExist
from tempo_tpu.backend.types import TenantIndex, CompactedBlockMeta
from tempo_tpu.encoding.v2 import (
    StreamingBlock,
    BackendBlock,
    ShardedBloom,
    IndexWriter,
    IndexReader,
    Record,
    compress,
    decompress,
)
from tempo_tpu.encoding.v2.index import IndexCorruptError
from tempo_tpu.encoding.v2.objects import (
    marshal_object,
    unmarshal_objects,
    ObjectFramingError,
)
from tempo_tpu.ops import native


from tempo_tpu.encoding.v2.compression import encoding_usable

ENCODINGS = ["none", "gzip", "zlib"] + (
    ["zstd"] if encoding_usable("zstd") else []
) + (
    ["lz4", "snappy"] if native.available() else []
)


@pytest.mark.parametrize("enc", ENCODINGS)
def test_compression_roundtrip(enc):
    data = os.urandom(1000) + b"A" * 5000
    assert decompress(compress(data, enc), enc) == data


def test_object_framing_roundtrip():
    objs = [(os.urandom(16), os.urandom(i * 7 + 1)) for i in range(20)]
    buf = b"".join(marshal_object(i, d) for i, d in objs)
    assert list(unmarshal_objects(buf)) == objs


def test_object_framing_truncation():
    buf = marshal_object(b"\x01" * 16, b"data") + b"\x00\x01"
    with pytest.raises(ObjectFramingError):
        list(unmarshal_objects(buf))
    got = list(unmarshal_objects(buf, tolerate_truncation=True))
    assert got == [(b"\x01" * 16, b"data")]


def test_index_roundtrip_and_find():
    recs = []
    off = 0
    for i in range(100):
        mid = (i * 10 + 9).to_bytes(16, "big")  # max id of page i
        recs.append(Record(mid, off, 100))
        off += 100
    data = IndexWriter(records_per_page=7).write(recs)
    rd = IndexReader(data)
    assert len(rd) == 100
    # id 55 falls in page 5 (ids 50..59 -> max 59)
    r = rd.find((55).to_bytes(16, "big"))
    assert r.start == 500
    # exact max id
    r = rd.find((9).to_bytes(16, "big"))
    assert r.start == 0
    # beyond all
    assert rd.find((2000).to_bytes(16, "big")) is None


def test_index_checksum_detects_corruption():
    recs = [Record(b"\x01" * 16, 0, 10)]
    data = bytearray(IndexWriter().write(recs))
    data[-1] ^= 0xFF
    with pytest.raises(IndexCorruptError):
        IndexReader(bytes(data))


def test_bloom_membership():
    b = ShardedBloom(shard_count=4, fp_rate=0.01, expected_per_shard=500)
    ids = [os.urandom(16) for _ in range(1000)]
    for i in ids:
        b.add(i)
    for i in ids:
        assert b.test(i)
    fp = sum(b.test(os.urandom(16)) for _ in range(2000))
    assert fp < 2000 * 0.05  # generous bound on fp rate


def test_bloom_marshalled_matches_inmemory():
    b = ShardedBloom(shard_count=3, expected_per_shard=100)
    ids = [os.urandom(16) for _ in range(200)]
    for i in ids:
        b.add(i)
    shards = [b.marshal_shard(s) for s in range(3)]
    for i in ids:
        s = ShardedBloom.shard_for(i, 3)
        assert ShardedBloom.test_marshalled(shards[s], i)


@pytest.mark.parametrize("enc", ["none", "zstd"])
def test_streaming_block_roundtrip(tmp_backend_dir, enc):
    if not encoding_usable(enc):
        pytest.skip(f"{enc} codec unavailable on this host")
    be = LocalBackend(tmp_backend_dir)
    meta = BlockMeta(tenant_id="t1", encoding=enc)
    sb = StreamingBlock(meta, page_size=2048)
    rng = random.Random(1)
    objs = sorted(
        (rng.randbytes(16), rng.randbytes(rng.randint(50, 500)))
        for _ in range(200)
    )
    for i, (oid, data) in enumerate(objs):
        sb.add_object(oid, data, start=100 + i, end=200 + i)
    out = sb.complete(be)
    assert out.total_objects == 200
    assert out.total_records > 1  # multiple pages
    assert out.start_time == 100 and out.end_time == 399

    bb = BackendBlock(be, be.read_block_meta("t1", out.block_id))
    # every object findable
    for oid, data in objs:
        assert bb.find_by_id(oid) == data
    # absent ids return None
    for _ in range(50):
        assert bb.find_by_id(rng.randbytes(16)) is None
    # full iteration returns everything in order
    got = list(bb.iter_objects())
    assert [o for o, _ in got] == [o for o, _ in objs]
    # page-range iteration covers a subset
    part = list(bb.iter_objects(start_page=1, pages=2))
    assert 0 < len(part) < 200


def test_streaming_block_rejects_unsorted(tmp_backend_dir):
    sb = StreamingBlock(BlockMeta(tenant_id="t1"))
    sb.add_object(b"\x05" * 16, b"x")
    with pytest.raises(ValueError):
        sb.add_object(b"\x01" * 16, b"y")


def test_block_meta_json_roundtrip():
    m = BlockMeta(tenant_id="t9", encoding="zstd", total_objects=5)
    m2 = BlockMeta.from_json(m.to_json())
    assert m2 == m
    cm = CompactedBlockMeta.from_meta(m)
    cm2 = CompactedBlockMeta.from_json(cm.to_json())
    assert cm2.meta == m and cm2.compacted_time == cm.compacted_time


def test_tenant_index_roundtrip():
    metas = [BlockMeta(tenant_id="t") for _ in range(3)]
    idx = TenantIndex(created_at=123, metas=metas,
                      compacted=[CompactedBlockMeta.from_meta(metas[0])])
    idx2 = TenantIndex.from_bytes(idx.to_bytes())
    assert idx2.created_at == 123
    assert [m.block_id for m in idx2.metas] == [m.block_id for m in metas]
    assert idx2.compacted[0].meta.block_id == metas[0].block_id


def test_backend_compacted_lifecycle(tmp_backend_dir):
    from tempo_tpu.encoding.v2.compression import best_available

    be = LocalBackend(tmp_backend_dir)
    # lifecycle under test, not the codec — degrade on codec-less hosts
    meta = BlockMeta(tenant_id="t1", encoding=best_available("zstd"))
    sb = StreamingBlock(meta)
    sb.add_object(b"\x01" * 16, b"hello")
    out = sb.complete(be)
    assert be.list_blocks("t1") == [out.block_id]
    be.mark_compacted(out)
    with pytest.raises(DoesNotExist):
        be.read_block_meta("t1", out.block_id)
    cm = be.read_compacted_meta("t1", out.block_id)
    assert cm.meta.block_id == out.block_id
    be.clear_block("t1", out.block_id)
    assert be.list_blocks("t1") == []


def test_mock_backend_matches_local(tmp_backend_dir):
    for be in (LocalBackend(tmp_backend_dir), MockBackend()):
        be.write("t", "b1", "data", b"abc")
        assert be.read("t", "b1", "data") == b"abc"
        assert be.read_range("t", "b1", "data", 1, 1) == b"b"
        assert be.list_tenants() == ["t"]
        assert be.list_blocks("t") == ["b1"]
        be.delete("t", "b1", "data")
        with pytest.raises(DoesNotExist):
            be.read("t", "b1", "data")
