"""Shape-bucketed cross-plan stacking (ISSUE 16): heterogeneous
structural plans canonicalize into a small static family of bucket
shapes (structural.canonical_bucket) so MIXED-plan concurrent queries
fuse into one coalesced dispatch — byte-identical to solo execution and
to the host reference evaluator, because each member's exact plan rides
along as a per-query slot program whose pad slots are unreachable from
the result slot."""

from __future__ import annotations

import random
import threading

import pytest

from tempo_tpu.search import ir
from tempo_tpu.search import packing as packing_mod
from tempo_tpu.search.columnar import ColumnarPages
from tempo_tpu.search.data import SearchData
from tempo_tpu.search.multiblock import (
    MultiBlockEngine,
    compile_multi,
    stack_queries,
)
from tempo_tpu.search.structural import (
    STRUCTURAL,
    BucketedStructural,
    canonical_bucket,
    compile_structural,
)
from test_structural import (  # noqa: F401 — _structural_on is autouse
    E_GEO,
    _corpus,
    _expected_ids,
    _mk_req,
    _mkdb,
    _rand_trace,
    _reparam,
    _scan_ids,
    _structural_on,
)

# three DISTINCT plan shapes that land in ONE bucket: same flattened
# span tier (tag/dur/kind leaf + tag/dur leaf + child = NS 4), same
# trace tier (exists + root copy = NT 2), all relational
_MIXED_TRIPLE = (
    '{"exists": {"child": {"parent": {"tag": {"k": "service.name", '
    '"v": "api"}}, "child": {"dur": {"min_ms": 50}}}}}',
    '{"exists": {"child": {"parent": {"tag": {"k": "service.name", '
    '"v": "db"}}, "child": {"kind": "server"}}}}',
    '{"exists": {"child": {"parent": {"dur": {"min_ms": 10}}, '
    '"child": {"tag": {"k": "name", "v": "op"}}}}}',
)


# --------------------------------------------- canonicalization (unit)


def test_canonical_bucket_tiers_and_solo_fallback():
    exprs = [ir.parse(s) for s in _MIXED_TRIPLE]
    entries = _corpus(21, n=40)
    blocks = [ColumnarPages.build(entries, E_GEO)]
    plans = [compile_structural(e, blocks).plan for e in exprs]
    assert len(set(plans)) == 3, "triple must be plan-heterogeneous"
    buckets = {canonical_bucket(p, STRUCTURAL.bucket_max_nodes)
               for p in plans}
    assert len(buckets) == 1
    bk = buckets.pop()
    assert bk[0] == "bucket" and bk[3] is True
    # pow2 tiers: 3 span slots -> 4, exists + root copy -> 2
    assert bk[1] == 4 and bk[2] == 2
    # relation-free plans bucket SEPARATELY (has_rel in the descriptor)
    flat = compile_structural(
        ir.parse('{"and": [{"tag": {"k": "env", "v": "prod"}}, '
                 '{"dur": {"min_ms": 5}}]}'), blocks).plan
    fb = canonical_bucket(flat, STRUCTURAL.bucket_max_nodes)
    assert fb is not None and fb[3] is False and fb != bk
    # over the tier cap the plan "still goes solo": exact-plan grouping
    assert canonical_bucket(plans[0], 2) is None


def test_bucket_group_key_gate_and_fallback():
    entries = _corpus(22, n=40)
    blocks = [ColumnarPages.build(entries, E_GEO)]
    eng = MultiBlockEngine(top_k=128)
    batch = eng.stage(blocks)
    sts = []
    for src in _MIXED_TRIPLE:
        expr = ir.parse(src)
        sts.append(compile_structural(expr, blocks, cache_on=batch))
    STRUCTURAL.stack_enabled = True
    # gate OFF: one attribute read, exact-plan grouping kept — the
    # three plans get three distinct group keys
    assert STRUCTURAL.bucket_enabled is False
    assert STRUCTURAL.bucket_group_key(batch, sts[0]) is None
    keys_off = {STRUCTURAL.stack_group_key(batch, st) for st in sts}
    assert len(keys_off) == 3
    assert keys_off == {(id(batch), st.plan) for st in sts}
    # gate ON: all three share ONE (batch, bucket) key
    STRUCTURAL.bucket_enabled = True
    keys_on = {STRUCTURAL.stack_group_key(batch, st) for st in sts}
    assert len(keys_on) == 1
    (bid, bk) = keys_on.pop()
    assert bid == id(batch) and bk[0] == "bucket"
    # a plan past the tier cap falls back to its exact plan key
    STRUCTURAL.bucket_max_nodes = 2
    assert STRUCTURAL.stack_group_key(batch, sts[0]) \
        == (id(batch), sts[0].plan)


# ------------------------------------------------ fused differential


def _check_bucketed(entries, exprs, packed: bool, mesh=None):
    """Mixed-plan differential: the bucket-fused dispatch answers
    bit-for-bit identically to solo dispatches and the host reference
    evaluator, per member lane."""
    from tempo_tpu.search.engine import fetch_coalesced_out

    packing_mod.PACKING.enabled = packed
    half = len(entries) // 2
    b1 = ColumnarPages.build(entries[:half], E_GEO)
    b2 = ColumnarPages.build(entries[half:], E_GEO)
    spanless = [SearchData(trace_id=(20_000 + i).to_bytes(16, "big"),
                           start_s=1, end_s=2, dur_ms=100,
                           kvs={"env": {"prod"}}) for i in range(5)]
    blocks = [b1, b2, ColumnarPages.build(spanless, E_GEO)]
    eng = MultiBlockEngine(top_k=512, mesh=mesh)
    batch = eng.stage(blocks)
    mqs = []
    for expr in exprs:
        req = _mk_req(expr)
        mq = compile_multi(blocks, req, cache_on=batch)
        mq.structural = compile_structural(
            expr, blocks, cache_on=batch,
            staged_dicts=batch.staged_dicts)
        mq._expr = expr
        mqs.append(mq)
    # group exactly like bucket_group_key: same canonical bucket
    groups: dict = {}
    for mq in mqs:
        bk = canonical_bucket(mq.structural.plan,
                              STRUCTURAL.bucket_max_nodes)
        if bk is not None:
            groups.setdefault(bk, []).append(mq)
    checked = 0
    all_entries = entries + spanless
    E = E_GEO.entries_per_page
    for bk, group in groups.items():
        if len(group) < 2:
            continue
        if len({mq.structural.plan for mq in group}) < 2:
            continue  # same-plan groups take the exact-plan stack
        cq = stack_queries(group)
        assert isinstance(cq.structural, BucketedStructural)
        assert cq.structural.plan == bk
        assert cq.structural.active_nodes <= cq.structural.slot_nodes
        counts, _ins, scores, idx = fetch_coalesced_out(
            eng.coalesced_scan_async(batch, cq, 512))
        for qi, mq in enumerate(group):
            got = set()
            for s, i in zip(scores[qi].tolist(), idx[qi].tolist()):
                if s < 0:
                    break
                p, e = divmod(i, E)
                if p >= batch.n_pages:
                    continue
                bi = int(batch.page_block[p])
                if bi < 0:
                    continue
                lp = p - batch.page_offset[bi]
                got.add(bytes(batch.blocks[bi].trace_ids[lp, e]))
            want = _expected_ids(mq._expr, all_entries)
            scount, sgot = _scan_ids(batch, eng, mq, all_entries)
            assert got == want == sgot, (ir.to_json(mq._expr), packed)
            assert int(counts[qi]) == len(want) == scount
        checked += len(group)
    return checked


@pytest.mark.parametrize("packed", [False, True])
def test_bucketed_mixed_triple_matches_solo_and_host(packed):
    entries = _corpus(31, n=120)
    exprs = [ir.parse(s) for s in _MIXED_TRIPLE]
    assert _check_bucketed(entries, exprs, packed=packed) == 3


@pytest.mark.parametrize("packed", [False, True])
def test_bucketed_differential_fuzz_mixed_plans(packed):
    """The bucketing property: ANY random mixed-plan concurrent set
    whose members canonicalize into one bucket answers identically
    bucket-fused, solo, and on the reference evaluator — packed
    residency on and off."""
    rng = random.Random(80_000 + packed)
    checked = 0
    for round_i in range(6):
        entries = _corpus(900 + round_i, n=70)
        # random templates plus reparams: reparamming preserves tree
        # SHAPE but leaf dedup may shift exact plans apart — precisely
        # the mixed-plan-same-bucket traffic bucketing fuses
        exprs = []
        for _ in range(3):
            t = _rand_trace(rng)
            exprs += [t, _reparam(t, rng), _reparam(t, rng)]
        checked += _check_bucketed(entries, exprs, packed=packed)
    assert checked >= 4, "fuzz never produced a mixed-plan bucket group"


def test_bucketed_on_mesh_with_sharded_spans():
    """Bucketed stacking composes with the mesh path and segment-
    aligned span sharding, byte-identical throughout."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple (forced host) devices")
    from tempo_tpu.parallel import make_mesh

    entries = _corpus(41, n=160)
    exprs = [ir.parse(s) for s in _MIXED_TRIPLE]
    mesh = make_mesh()
    STRUCTURAL.shard_spans = True
    try:
        assert _check_bucketed(entries, exprs, packed=False,
                               mesh=mesh) == 3
    finally:
        STRUCTURAL.shard_spans = False


def test_mixed_plans_without_shared_bucket_still_raise():
    """stack_queries keeps its caller-bug contract: a mixed group whose
    members do NOT canonicalize into one bucket raises rather than
    silently dropping a predicate."""
    entries = _corpus(51, n=40)
    blocks = [ColumnarPages.build(entries, E_GEO)]
    eng = MultiBlockEngine(top_k=128)
    batch = eng.stage(blocks)
    mqs = []
    for src in (_MIXED_TRIPLE[0],
                '{"tag": {"k": "env", "v": "prod"}}'):  # different bucket
        expr = ir.parse(src)
        req = _mk_req(expr)
        mq = compile_multi(blocks, req, cache_on=batch)
        mq.structural = compile_structural(expr, blocks, cache_on=batch)
        mqs.append(mq)
    with pytest.raises(ValueError, match="bucket"):
        stack_queries(mqs)


# ------------------------------------------------- serving path


def test_serving_path_fuses_mixed_plan_queries(tmp_path):
    """8 concurrent MIXED-plan structural searches through the full
    serving path fuse under the bucket gate: byte-identical to serial,
    result=stacked_bucketed booked, and /debug/scan shows per-bucket
    stack ratios + occupancy."""
    from tempo_tpu.observability import metrics as obs

    entries = _corpus(61, n=120)
    db = _mkdb(tmp_path, entries,
               search_structural_stack_enabled=True,
               search_structural_bucket_enabled=True,
               search_coalesce_window_s=0.05)
    assert STRUCTURAL.bucket_enabled is True
    srcs = [_MIXED_TRIPLE[i % 3] for i in range(8)]
    exprs = [ir.parse(s) for s in srcs]
    # reparam the repeats so every request is a distinct query while
    # the SHAPES still span >= 3 distinct plans in one bucket
    rng = random.Random(7)
    exprs = [e if i < 3 else _reparam(exprs[i % 3], rng)
             for i, e in enumerate(exprs)]

    def canon(resp):
        resp.metrics.device_seconds = 0
        return resp.SerializeToString()

    serial = []
    for e in exprs:
        r = _mk_req(e, limit=1000)
        serial.append(canon(db.search("t", r).response()))
    co = db.batcher.coalescer
    base_bucketed = co.structural_bucketed
    ev0 = obs.structural_stack_events.value(result="stacked_bucketed")
    out = [None] * len(exprs)
    barrier = threading.Barrier(len(exprs))

    def one(i):
        r = _mk_req(exprs[i], limit=1000)
        barrier.wait()
        out[i] = canon(db.search("t", r).response())

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(len(exprs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(len(exprs)):
        assert out[i] == serial[i], f"query {i} diverged under bucketing"
    assert co.structural_bucketed > base_bucketed, "no bucketed fusion"
    assert obs.structural_stack_events.value(
        result="stacked_bucketed") > ev0
    stats = co.stats()
    assert stats["structural_bucketed"] > 0
    assert stats["buckets"], "per-bucket stats missing"
    row = next(iter(stats["buckets"].values()))
    assert row["stack_ratio"] > 1
    assert 0 < row["occupancy"] <= 1
    dbg = db.batcher.debug_stats()
    assert dbg["coalesce"]["structural_bucketed"] \
        == co.structural_bucketed
