import time

import pytest

from tempo_tpu import tempopb
from tempo_tpu.backend import BlockMeta, LocalBackend, MockBackend, DoesNotExist
from tempo_tpu.db import TempoDB, TempoDBConfig, Poller, TimeWindowBlockSelector
from tempo_tpu.db.pool import run_jobs
from tempo_tpu.model import codec_for, segment_codec_for
from tempo_tpu.search import extract_search_data
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.utils.test_data import make_trace

from tests.test_search import _mk_req


def _ingest(db, tenant, n, seed_base=0):
    """Push n traces through WAL + search extraction, complete the block."""
    blk = db.wal.new_block(tenant)
    sc = segment_codec_for("v2")
    entries = {}
    traces = {}
    for i in range(n):
        tid = random_trace_id()
        tr = make_trace(tid, seed=seed_base + i)
        sd = extract_search_data(tid, tr)
        seg = sc.prepare_for_write(tr, sd.start_s, sd.end_s)
        blk.append(tid, seg, sd.start_s, sd.end_s)
        entries[tid] = sd
        traces[tid] = tr
    meta = db.complete_block(
        blk, [entries[t] for t in sorted(entries)]
    )
    blk.clear()
    return meta, traces


def _db(tmp_path, **cfg):
    be = LocalBackend(str(tmp_path / "blocks"))
    return TempoDB(be, str(tmp_path / "wal"), TempoDBConfig(**cfg))


def test_run_jobs_early_stop_and_errors():
    calls = []

    def fn(x):
        calls.append(x)
        if x == 3:
            raise RuntimeError("boom")
        return x if x == 5 else None

    results, errors = run_jobs(list(range(10)), fn, workers=1, stop_on_first=True)
    assert results == [5]
    assert len(errors) == 1
    assert len(calls) <= 7  # stopped early


def test_complete_block_and_find(tmp_path):
    db = _db(tmp_path)
    meta, traces = _ingest(db, "t1", 50)
    assert meta.total_objects == 50

    c = codec_for("v2")
    for tid, tr in list(traces.items())[:10]:
        obj, failed = db.find_trace_by_id("t1", tid)
        assert obj is not None and failed == 0
        assert c.prepare_for_read(obj) == tr
    assert db.find_trace_by_id("t1", b"\x42" * 16)[0] is None


def test_find_combines_across_blocks(tmp_path):
    """Same trace id in two blocks (pre-compaction) → combined on read."""
    db = _db(tmp_path)
    tid = random_trace_id()
    sc = segment_codec_for("v2")
    for seed in (1, 2):
        blk = db.wal.new_block("t1")
        tr = make_trace(tid, seed=seed, batches=1)
        blk.append(tid, sc.prepare_for_write(tr, 10, 20), 10, 20)
        db.complete_block(blk)
        blk.clear()
    obj, _ = db.find_trace_by_id("t1", tid)
    got = codec_for("v2").prepare_for_read(obj)
    assert len(got.batches) == 2


def test_search_across_blocks_with_limit(tmp_path):
    db = _db(tmp_path)
    for i in range(3):
        _ingest(db, "t1", 40, seed_base=i * 100)
    req = _mk_req({})  # match-all
    req.limit = 25
    res = db.search("t1", req)
    resp = res.response()
    assert len(resp.traces) == 25
    # early stop: not all 3 blocks necessarily inspected
    assert resp.metrics.inspected_blocks <= 3


def test_search_block_request_protocol(tmp_path):
    db = _db(tmp_path)
    meta, traces = _ingest(db, "t1", 30)
    req = tempopb.SearchBlockRequest()
    req.tenant_id = "t1"
    req.block_id = meta.block_id
    req.encoding = db.cfg.search_encoding
    req.version = meta.version
    req.data_encoding = meta.data_encoding
    req.search_req.limit = 50
    res = db.search_block(req)
    assert len(res.response().traces) == 30


def test_poller_tenant_index_roundtrip(tmp_path):
    db = _db(tmp_path)
    _ingest(db, "t1", 5)
    _ingest(db, "t2", 3)
    metas, compacted = db.poller.poll()
    assert {t: len(m) for t, m in metas.items()} == {"t1": 1, "t2": 1}

    # a reader (non-builder) uses the index written by the builder
    reader = Poller(db.backend, build_index=False)
    m2, c2 = reader.poll()
    assert [m.block_id for m in m2["t1"]] == [m.block_id for m in metas["t1"]]

    db.poll()
    assert db.blocklist.tenants() == ["t1", "t2"]


def test_selector_groups_by_level_and_window():
    sel = TimeWindowBlockSelector(window_s=100, min_inputs=2, max_inputs=3)
    now = 10_000

    def meta(end, level=0, size=10):
        m = BlockMeta(tenant_id="t", compaction_level=level)
        m.end_time = end
        m.size = size
        return m

    # 4 blocks in one window, level 0 → picks 3 (max_inputs)
    metas = [meta(9_950) for _ in range(4)]
    picked = sel.blocks_to_compact(metas, now)
    assert len(picked) == 3

    # different levels in active window don't mix
    metas = [meta(9_950, level=0), meta(9_950, level=1)]
    assert sel.blocks_to_compact(metas, now) == []

    # outside the active window levels DO mix
    old = now - 25 * 3600
    metas = [meta(old, level=0), meta(old, level=1)]
    assert len(sel.blocks_to_compact(metas, now)) == 2

    # single block never compacts
    assert sel.blocks_to_compact([meta(9_950)], now) == []


def test_compaction_merges_and_dedupes(tmp_path):
    db = _db(tmp_path, compaction_window_s=10_000_000_000)
    shared = random_trace_id()
    sc = segment_codec_for("v2")

    metas = []
    for seed in (1, 2):
        blk = db.wal.new_block("t1")
        tr = make_trace(shared, seed=seed, batches=1)
        blk.append(shared, sc.prepare_for_write(tr, 100, 200), 100, 200)
        for i in range(10):
            tid = random_trace_id()
            tr = make_trace(tid, seed=seed * 50 + i)
            sd = extract_search_data(tid, tr)
            blk.append(tid, sc.prepare_for_write(tr, sd.start_s, sd.end_s),
                       sd.start_s, sd.end_s)
        sds = {}
        # rebuild search entries for completeness
        metas.append(db.complete_block(blk))
        blk.clear()

    new_meta = db.compact_tenant_once("t1", now_s=250)
    assert new_meta is not None
    assert new_meta.compaction_level == 1
    assert new_meta.total_objects == 21  # 10 + 10 + 1 shared (deduped)

    # inputs are marked compacted on the backend
    for m in metas:
        with pytest.raises(DoesNotExist):
            db.backend.read_block_meta("t1", m.block_id)
        assert db.backend.read_compacted_meta("t1", m.block_id)

    # blocklist staged update took effect
    live = db.blocklist.metas("t1")
    assert [m.block_id for m in live] == [new_meta.block_id]

    # the shared trace combined both batches
    obj, _ = db.find_trace_by_id("t1", shared)
    assert len(codec_for("v2").prepare_for_read(obj).batches) == 2


def test_compaction_preserves_search(tmp_path):
    """Unlike the reference (which drops search data at compaction), the
    merged block gets a rebuilt columnar search block."""
    db = _db(tmp_path, compaction_window_s=10_000_000_000)
    all_traces = {}
    for i in range(2):
        _, traces = _ingest(db, "t1", 20, seed_base=i * 1000)
        all_traces.update(traces)
    new_meta = db.compact_tenant_once("t1", now_s=int(time.time()))
    assert new_meta is not None

    req = _mk_req({})
    req.limit = 100
    res = db.search("t1", req)
    assert len(res.response().traces) == 40


def test_retention_two_phase(tmp_path):
    db = _db(tmp_path, retention_s=1000, compacted_retention_s=500)
    meta, _ = _ingest(db, "t1", 5)
    now = meta.end_time + 2000  # past retention

    marked, deleted = db.retain_tenant("t1", now_s=now)
    assert marked == 1 and deleted == 0
    assert db.blocklist.metas("t1") == []

    # second phase after compacted retention passes
    cm = db.backend.read_compacted_meta("t1", meta.block_id)
    marked2, deleted2 = db.retain_tenant("t1", now_s=cm.compacted_time + 1000)
    assert deleted2 == 1
    assert db.backend.list_blocks("t1") == []


def test_search_batched_pipeline(tmp_path):
    """The serving path batches many blocks into FEW kernel dispatches
    (the round-2 wiring of MultiBlockEngine into TempoDB.search), with
    results identical to the per-block job path, early quit across
    groups, and zero dispatches for fully pruned queries."""
    from tempo_tpu.search.multiblock import MultiBlockEngine

    db = _db(tmp_path)
    for b in range(5):
        _ingest(db, "t1", 6, seed_base=b * 100)
    db.poll()
    metas = db.blocklist.metas("t1")
    assert len(metas) == 5

    dispatches = []
    orig = MultiBlockEngine.scan_async

    def counting(self, batch, mq):
        dispatches.append(len(batch.blocks))
        return orig(self, batch, mq)

    MultiBlockEngine.scan_async = counting
    try:
        req = _mk_req({})
        req.limit = 1000
        r_batched = db.search("t1", req)
        # 5 blocks, one geometry bucket, under the page budget → 1 dispatch
        assert dispatches == [5]

        # per-block jobs (the SearchBlockRequest protocol path) agree
        per_block = set()
        for m in metas:
            breq = tempopb.SearchBlockRequest()
            breq.search_req.CopyFrom(req)
            breq.tenant_id = "t1"
            breq.block_id = m.block_id
            breq.encoding = m.encoding
            breq.version = m.version
            breq.data_encoding = m.data_encoding
            for t in db.search_block(breq).response().traces:
                per_block.add(t.trace_id)
        batched_ids = {t.trace_id for t in r_batched.response().traces}
        assert len(batched_ids) == 30 and batched_ids == per_block

        # early quit: force one group per block; a small limit stops
        # dispatching before all groups run
        db.batcher.max_batch_pages = 1
        db.batcher._cache.clear()
        db.batcher._cache_total = 0
        dispatches.clear()
        small = _mk_req({})
        small.limit = 3
        r = db.search("t1", small)
        assert r.complete and len(r.response().traces) >= 3
        assert len(dispatches) < 5  # stopped early

        # fully pruned query (future time window): no device work at all
        dispatches.clear()
        future = _mk_req({})
        future.start = 2**31 - 10
        future.end = 2**31 - 1
        r = db.search("t1", future)
        assert not dispatches
        assert r.metrics.skipped_blocks >= 5
    finally:
        MultiBlockEngine.scan_async = orig


def test_streaming_compaction_bounded_memory(tmp_path):
    """Compaction of inputs ≫ flush size streams through backend.append:
    peak RSS stays far below the output block size, and the result is
    identical to the fully-buffered path (VERDICT r1 #3)."""
    import resource

    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db.compaction import compact_blocks
    from tempo_tpu.encoding.v2 import BackendBlock, StreamingBlock
    from tempo_tpu.backend.types import BlockMeta

    def build_inputs(be, n_blocks=3, objs_per_block=40, obj_kb=64):
        metas = []
        rows = []
        for b in range(n_blocks):
            m = BlockMeta(tenant_id="t1", encoding="none")
            sb = StreamingBlock(m, page_size=32 << 10)
            for i in range(objs_per_block):
                oid = bytes([b]) + bytes([i]) * 15
                data = (bytes([b, i]) * (obj_kb * 512))  # obj_kb KiB
                sb.add_object(oid, data)
                rows.append((oid, data))
            metas.append(sb.complete(be))
        return metas, rows

    be1 = LocalBackend(str(tmp_path / "stream"))
    metas1, rows = build_inputs(be1)
    total_in = sum(m.size for m in metas1)
    flush = 256 << 10  # 256 KiB flush vs ~7.5 MiB of input
    assert total_in > 8 * flush

    rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    out1 = compact_blocks(be1, "t1", metas1, page_size=32 << 10,
                          compact_search=False, flush_size=flush)
    rss_after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # ru_maxrss is KiB on linux; allow generous slack for allocator noise,
    # but far below the ~7.5MiB output that round 1 held fully in RAM
    assert (rss_after - rss_before) * 1024 < total_in // 2, (
        rss_before, rss_after, total_in)

    be2 = LocalBackend(str(tmp_path / "buffered"))
    metas2, _ = build_inputs(be2)
    out2 = compact_blocks(be2, "t1", metas2, page_size=32 << 10,
                          compact_search=False, flush_size=1 << 40)

    d1 = be1.read("t1", out1.block_id, "data")
    d2 = be2.read("t1", out2.block_id, "data")
    assert d1 == d2
    assert out1.size == out2.size == len(d1)
    assert out1.total_objects == out2.total_objects == len(rows)
    for oid, data in rows[::13]:
        assert BackendBlock(be1, out1).find_by_id(oid) == data


def test_search_compaction_kway_merge_identical(tmp_path):
    """The spill-file k-way search-data merge produces the same merged
    container as the round-1 in-memory dict approach (same ids, tags,
    ranges), including cross-block duplicate combination."""
    db = _db(tmp_path, compaction_window_s=10_000_000_000)
    import time as _t

    all_traces = {}
    for i in range(3):
        _, traces = _ingest(db, "t1", 15, seed_base=i * 500)
        all_traces.update(traces)
    new_meta = db.compact_tenant_once("t1", now_s=int(_t.time()))
    assert new_meta is not None
    assert new_meta.search_pages > 0  # merged container committed to meta

    req = _mk_req({})
    req.limit = 200
    res = db.search("t1", req)
    assert len(res.response().traces) == len(all_traces)
