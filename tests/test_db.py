import time

import pytest

from tempo_tpu import tempopb
from tempo_tpu.backend import BlockMeta, LocalBackend, MockBackend, DoesNotExist
from tempo_tpu.db import TempoDB, TempoDBConfig, Poller, TimeWindowBlockSelector
from tempo_tpu.db.pool import run_jobs
from tempo_tpu.model import codec_for, segment_codec_for
from tempo_tpu.search import extract_search_data
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.utils.test_data import make_trace

from tests.test_search import _mk_req


def _ingest(db, tenant, n, seed_base=0):
    """Push n traces through WAL + search extraction, complete the block."""
    blk = db.wal.new_block(tenant)
    sc = segment_codec_for("v2")
    entries = {}
    traces = {}
    for i in range(n):
        tid = random_trace_id()
        tr = make_trace(tid, seed=seed_base + i)
        sd = extract_search_data(tid, tr)
        seg = sc.prepare_for_write(tr, sd.start_s, sd.end_s)
        blk.append(tid, seg, sd.start_s, sd.end_s)
        entries[tid] = sd
        traces[tid] = tr
    meta = db.complete_block(
        blk, [entries[t] for t in sorted(entries)]
    )
    blk.clear()
    return meta, traces


def _db(tmp_path, **cfg):
    be = LocalBackend(str(tmp_path / "blocks"))
    return TempoDB(be, str(tmp_path / "wal"), TempoDBConfig(**cfg))


def test_run_jobs_early_stop_and_errors():
    calls = []

    def fn(x):
        calls.append(x)
        if x == 3:
            raise RuntimeError("boom")
        return x if x == 5 else None

    results, errors = run_jobs(list(range(10)), fn, workers=1, stop_on_first=True)
    assert results == [5]
    assert len(errors) == 1
    assert len(calls) <= 7  # stopped early


def test_complete_block_and_find(tmp_path):
    db = _db(tmp_path)
    meta, traces = _ingest(db, "t1", 50)
    assert meta.total_objects == 50

    c = codec_for("v2")
    for tid, tr in list(traces.items())[:10]:
        obj, failed = db.find_trace_by_id("t1", tid)
        assert obj is not None and failed == 0
        assert c.prepare_for_read(obj) == tr
    assert db.find_trace_by_id("t1", b"\x42" * 16)[0] is None


def test_find_combines_across_blocks(tmp_path):
    """Same trace id in two blocks (pre-compaction) → combined on read."""
    db = _db(tmp_path)
    tid = random_trace_id()
    sc = segment_codec_for("v2")
    for seed in (1, 2):
        blk = db.wal.new_block("t1")
        tr = make_trace(tid, seed=seed, batches=1)
        blk.append(tid, sc.prepare_for_write(tr, 10, 20), 10, 20)
        db.complete_block(blk)
        blk.clear()
    obj, _ = db.find_trace_by_id("t1", tid)
    got = codec_for("v2").prepare_for_read(obj)
    assert len(got.batches) == 2


def test_search_across_blocks_with_limit(tmp_path):
    db = _db(tmp_path)
    for i in range(3):
        _ingest(db, "t1", 40, seed_base=i * 100)
    req = _mk_req({})  # match-all
    req.limit = 25
    res = db.search("t1", req)
    resp = res.response()
    assert len(resp.traces) == 25
    # early stop: not all 3 blocks necessarily inspected
    assert resp.metrics.inspected_blocks <= 3


def test_search_block_request_protocol(tmp_path):
    db = _db(tmp_path)
    meta, traces = _ingest(db, "t1", 30)
    req = tempopb.SearchBlockRequest()
    req.tenant_id = "t1"
    req.block_id = meta.block_id
    req.encoding = db.cfg.search_encoding
    req.version = meta.version
    req.data_encoding = meta.data_encoding
    req.search_req.limit = 50
    res = db.search_block(req)
    assert len(res.response().traces) == 30


def test_poller_tenant_index_roundtrip(tmp_path):
    db = _db(tmp_path)
    _ingest(db, "t1", 5)
    _ingest(db, "t2", 3)
    metas, compacted = db.poller.poll()
    assert {t: len(m) for t, m in metas.items()} == {"t1": 1, "t2": 1}

    # a reader (non-builder) uses the index written by the builder
    reader = Poller(db.backend, build_index=False)
    m2, c2 = reader.poll()
    assert [m.block_id for m in m2["t1"]] == [m.block_id for m in metas["t1"]]

    db.poll()
    assert db.blocklist.tenants() == ["t1", "t2"]


def test_selector_groups_by_level_and_window():
    sel = TimeWindowBlockSelector(window_s=100, min_inputs=2, max_inputs=3)
    now = 10_000

    def meta(end, level=0, size=10):
        m = BlockMeta(tenant_id="t", compaction_level=level)
        m.end_time = end
        m.size = size
        return m

    # 4 blocks in one window, level 0 → picks 3 (max_inputs)
    metas = [meta(9_950) for _ in range(4)]
    picked = sel.blocks_to_compact(metas, now)
    assert len(picked) == 3

    # different levels in active window don't mix
    metas = [meta(9_950, level=0), meta(9_950, level=1)]
    assert sel.blocks_to_compact(metas, now) == []

    # outside the active window levels DO mix
    old = now - 25 * 3600
    metas = [meta(old, level=0), meta(old, level=1)]
    assert len(sel.blocks_to_compact(metas, now)) == 2

    # single block never compacts
    assert sel.blocks_to_compact([meta(9_950)], now) == []


def test_compaction_merges_and_dedupes(tmp_path):
    db = _db(tmp_path, compaction_window_s=10_000_000_000)
    shared = random_trace_id()
    sc = segment_codec_for("v2")

    metas = []
    for seed in (1, 2):
        blk = db.wal.new_block("t1")
        tr = make_trace(shared, seed=seed, batches=1)
        blk.append(shared, sc.prepare_for_write(tr, 100, 200), 100, 200)
        for i in range(10):
            tid = random_trace_id()
            tr = make_trace(tid, seed=seed * 50 + i)
            sd = extract_search_data(tid, tr)
            blk.append(tid, sc.prepare_for_write(tr, sd.start_s, sd.end_s),
                       sd.start_s, sd.end_s)
        sds = {}
        # rebuild search entries for completeness
        metas.append(db.complete_block(blk))
        blk.clear()

    new_meta = db.compact_tenant_once("t1", now_s=250)
    assert new_meta is not None
    assert new_meta.compaction_level == 1
    assert new_meta.total_objects == 21  # 10 + 10 + 1 shared (deduped)

    # inputs are marked compacted on the backend
    for m in metas:
        with pytest.raises(DoesNotExist):
            db.backend.read_block_meta("t1", m.block_id)
        assert db.backend.read_compacted_meta("t1", m.block_id)

    # blocklist staged update took effect
    live = db.blocklist.metas("t1")
    assert [m.block_id for m in live] == [new_meta.block_id]

    # the shared trace combined both batches
    obj, _ = db.find_trace_by_id("t1", shared)
    assert len(codec_for("v2").prepare_for_read(obj).batches) == 2


def test_compaction_preserves_search(tmp_path):
    """Unlike the reference (which drops search data at compaction), the
    merged block gets a rebuilt columnar search block."""
    db = _db(tmp_path, compaction_window_s=10_000_000_000)
    all_traces = {}
    for i in range(2):
        _, traces = _ingest(db, "t1", 20, seed_base=i * 1000)
        all_traces.update(traces)
    new_meta = db.compact_tenant_once("t1", now_s=int(time.time()))
    assert new_meta is not None

    req = _mk_req({})
    req.limit = 100
    res = db.search("t1", req)
    assert len(res.response().traces) == 40


def test_retention_two_phase(tmp_path):
    db = _db(tmp_path, retention_s=1000, compacted_retention_s=500)
    meta, _ = _ingest(db, "t1", 5)
    now = meta.end_time + 2000  # past retention

    marked, deleted = db.retain_tenant("t1", now_s=now)
    assert marked == 1 and deleted == 0
    assert db.blocklist.metas("t1") == []

    # second phase after compacted retention passes
    cm = db.backend.read_compacted_meta("t1", meta.block_id)
    marked2, deleted2 = db.retain_tenant("t1", now_s=cm.compacted_time + 1000)
    assert deleted2 == 1
    assert db.backend.list_blocks("t1") == []


def test_search_batched_pipeline(tmp_path):
    """The serving path batches many blocks into FEW kernel dispatches
    (the round-2 wiring of MultiBlockEngine into TempoDB.search), with
    results identical to the per-block job path, early quit across
    groups, and zero dispatches for fully pruned queries."""
    from tempo_tpu.search.multiblock import MultiBlockEngine

    db = _db(tmp_path)
    for b in range(5):
        _ingest(db, "t1", 6, seed_base=b * 100)
    db.poll()
    metas = db.blocklist.metas("t1")
    assert len(metas) == 5

    dispatches = []
    orig = MultiBlockEngine.scan_async

    def counting(self, batch, mq):
        dispatches.append(len(batch.blocks))
        return orig(self, batch, mq)

    MultiBlockEngine.scan_async = counting
    try:
        req = _mk_req({})
        req.limit = 1000
        r_batched = db.search("t1", req)
        # 5 blocks, one geometry bucket, under the page budget → 1 dispatch
        assert dispatches == [5]

        # per-block jobs (the SearchBlockRequest protocol path) agree
        per_block = set()
        for m in metas:
            breq = tempopb.SearchBlockRequest()
            breq.search_req.CopyFrom(req)
            breq.tenant_id = "t1"
            breq.block_id = m.block_id
            breq.encoding = m.encoding
            breq.version = m.version
            breq.data_encoding = m.data_encoding
            for t in db.search_block(breq).response().traces:
                per_block.add(t.trace_id)
        batched_ids = {t.trace_id for t in r_batched.response().traces}
        assert len(batched_ids) == 30 and batched_ids == per_block

        # early quit: force one group per block; a small limit stops
        # dispatching before all groups run
        db.batcher.max_batch_pages = 1
        db.batcher._cache.clear()
        db.batcher._cache_total = 0
        dispatches.clear()
        small = _mk_req({})
        small.limit = 3
        r = db.search("t1", small)
        assert r.complete and len(r.response().traces) >= 3
        assert len(dispatches) < 5  # stopped early

        # fully pruned query (future time window): no device work at all
        dispatches.clear()
        future = _mk_req({})
        future.start = 2**31 - 10
        future.end = 2**31 - 1
        r = db.search("t1", future)
        assert not dispatches
        assert r.metrics.skipped_blocks >= 5
    finally:
        MultiBlockEngine.scan_async = orig


def test_streaming_compaction_bounded_memory(tmp_path):
    """Compaction of inputs ≫ flush size streams through backend.append:
    peak RSS stays far below the output block size, and the result is
    identical to the fully-buffered path (VERDICT r1 #3)."""
    import resource

    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db.compaction import compact_blocks
    from tempo_tpu.encoding.v2 import BackendBlock, StreamingBlock
    from tempo_tpu.backend.types import BlockMeta

    def build_inputs(be, n_blocks=3, objs_per_block=40, obj_kb=64):
        metas = []
        rows = []
        for b in range(n_blocks):
            m = BlockMeta(tenant_id="t1", encoding="none")
            sb = StreamingBlock(m, page_size=32 << 10)
            for i in range(objs_per_block):
                oid = bytes([b]) + bytes([i]) * 15
                data = (bytes([b, i]) * (obj_kb * 512))  # obj_kb KiB
                sb.add_object(oid, data)
                rows.append((oid, data))
            metas.append(sb.complete(be))
        return metas, rows

    be1 = LocalBackend(str(tmp_path / "stream"))
    metas1, rows = build_inputs(be1)
    total_in = sum(m.size for m in metas1)
    flush = 256 << 10  # 256 KiB flush vs ~7.5 MiB of input
    assert total_in > 8 * flush

    rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    out1 = compact_blocks(be1, "t1", metas1, page_size=32 << 10,
                          compact_search=False, flush_size=flush)
    rss_after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # ru_maxrss is KiB on linux; allow generous slack for allocator noise,
    # but far below the ~7.5MiB output that round 1 held fully in RAM
    assert (rss_after - rss_before) * 1024 < total_in // 2, (
        rss_before, rss_after, total_in)

    be2 = LocalBackend(str(tmp_path / "buffered"))
    metas2, _ = build_inputs(be2)
    out2 = compact_blocks(be2, "t1", metas2, page_size=32 << 10,
                          compact_search=False, flush_size=1 << 40)

    d1 = be1.read("t1", out1.block_id, "data")
    d2 = be2.read("t1", out2.block_id, "data")
    assert d1 == d2
    assert out1.size == out2.size == len(d1)
    assert out1.total_objects == out2.total_objects == len(rows)
    for oid, data in rows[::13]:
        assert BackendBlock(be1, out1).find_by_id(oid) == data


def test_search_compaction_kway_merge_identical(tmp_path):
    """The spill-file k-way search-data merge produces the same merged
    container as the round-1 in-memory dict approach (same ids, tags,
    ranges), including cross-block duplicate combination."""
    db = _db(tmp_path, compaction_window_s=10_000_000_000)
    import time as _t

    all_traces = {}
    for i in range(3):
        _, traces = _ingest(db, "t1", 15, seed_base=i * 500)
        all_traces.update(traces)
    new_meta = db.compact_tenant_once("t1", now_s=int(_t.time()))
    assert new_meta is not None
    assert new_meta.search_pages > 0  # merged container committed to meta

    req = _mk_req({})
    req.limit = 200
    res = db.search("t1", req)
    assert len(res.response().traces) == len(all_traces)


def _synthetic_jobs(n, n_pages=64, prefix="blk"):
    from tempo_tpu.search.batcher import ScanJob

    return [
        ScanJob(key=(f"{prefix}-{i:04d}", 0, n_pages), pages_fn=None,
                header={"n_pages": n_pages}, n_pages=n_pages,
                n_entries=n_pages * 16, geometry=(16, 8))
        for i in range(n)
    ]


def test_batch_grouping_churn_local():
    """Adding one block to a 64-block tenant must invalidate O(1) cached
    groups, not every group after the new uuid's sort position (VERDICT
    round-2 weak #3: content-defined group boundaries)."""
    from tempo_tpu.search.batcher import BlockBatcher

    b = BlockBatcher(max_batch_pages=512)  # ~8 jobs/group ceiling
    jobs = _synthetic_jobs(64)
    before = {tuple(j.key for j in g) for g in b.plan(jobs)}
    assert len(before) > 4  # grouping actually splits

    # insert one new block in the MIDDLE of the id ordering
    from tempo_tpu.search.batcher import ScanJob
    new = ScanJob(key=("blk-0031a", 0, 64), pages_fn=None,
                  header={"n_pages": 64}, n_pages=64,
                  n_entries=64 * 16, geometry=(16, 8))
    after = {tuple(j.key for j in g) for g in b.plan(jobs + [new])}
    # every group not containing the new block's neighborhood survives
    changed = before - after
    assert len(changed) <= 2, (
        f"{len(changed)} of {len(before)} groups changed; boundaries "
        "are not churn-local"
    )

    # determinism: same jobs → identical groups
    again = {tuple(j.key for j in g) for g in b.plan(list(reversed(jobs)))}
    assert again == before


def test_batch_grouping_respects_page_cap_and_geometry():
    from tempo_tpu.search.batcher import BlockBatcher

    b = BlockBatcher(max_batch_pages=512)
    jobs = _synthetic_jobs(40) + _synthetic_jobs(8, n_pages=300, prefix="big")
    groups = b.plan(jobs)
    for g in groups:
        assert sum(j.n_pages for j in g) <= 512
        assert len({j.geometry for j in g}) == 1
    # every job appears exactly once
    flat = [j.key for g in groups for j in g]
    assert sorted(flat) == sorted(j.key for j in jobs)


def test_batcher_cache_hits_survive_blocklist_churn(tmp_path):
    """End-to-end churn test: search a cached multi-block tenant, add one
    block, poll (which invalidates dead groups), search again — the
    unaffected groups must HIT (VERDICT: hit-rate stays high across a
    poll in a churn test)."""
    import random
    import uuid as _uuid
    from unittest import mock

    from tempo_tpu.observability import metrics as obs

    # deterministic block ids: the churn locality bound depends on where
    # the new uuid lands among the anchors — seed it so the assertion is
    # exact, not a tail-probability
    rng = random.Random(42)
    patcher = mock.patch.object(
        _uuid, "uuid4", side_effect=lambda: _uuid.UUID(int=rng.getrandbits(128)))
    patcher.start()
    try:
        _run_churn_body(tmp_path, obs)
    finally:
        patcher.stop()


def _run_churn_body(tmp_path, obs):
    db = _db(tmp_path)
    db.batcher.max_batch_pages = 8  # force multiple groups (1 page/block)
    for b in range(12):
        _ingest(db, "t1", 4, seed_base=b * 50)
    db.poll()
    req = _mk_req({})
    req.limit = 10_000
    db.search("t1", req)  # populate the staged cache

    def counts():
        return (obs.batch_cache_events.value(result="hit"),
                obs.batch_cache_events.value(result="miss"))

    h0, m0 = counts()
    _ingest(db, "t1", 4, seed_base=999)  # churn: one new block
    db.poll()
    db.search("t1", req)
    h1, m1 = counts()
    # churn is LOCAL: the new block restages its own group (split → 2) and
    # the min-group-size guard can propagate the cut past one more anchor
    # — but never across the tenant (12 groups would all miss pre-fix)
    assert m1 - m0 <= 4, f"churn restaged {m1 - m0} groups"
    assert h1 - h0 >= 1


def test_staging_concurrent_misses_deduped(tmp_path):
    """Two threads missing on the same group must do the stage once
    (ADVICE r2: per-key in-progress event)."""
    import threading
    from tempo_tpu.observability import metrics as obs

    db = _db(tmp_path)
    for b in range(3):
        _ingest(db, "t1", 4, seed_base=b * 50)
    db.poll()

    def counts():
        return (obs.batch_cache_events.value(result="hit"),
                obs.batch_cache_events.value(result="miss"))

    h0, m0 = counts()
    req = _mk_req({})
    req.limit = 10_000
    barrier = threading.Barrier(4)
    errs = []

    def go():
        try:
            barrier.wait()
            db.search("t1", req)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=go) for _ in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs
    h1, m1 = counts()
    assert m1 - m0 == 1, f"expected exactly one stage, got {m1 - m0} misses"
    assert h1 - h0 >= 3


def test_search_blocks_drops_zero_page_jobs(tmp_path):
    """Stale metas can produce jobs whose page range is past the
    container; they must be filtered, not staged as empty batches."""
    db = _db(tmp_path)
    meta, _ = _ingest(db, "t1", 4)
    db.poll()
    breq = tempopb.SearchBlocksRequest()
    breq.search_req.CopyFrom(_mk_req({}))
    breq.tenant_id = "t1"
    j = breq.jobs.add()
    j.block_id = meta.block_id
    j.start_page = 10_000  # beyond the container
    j.pages_to_search = 5
    j.encoding = meta.encoding
    j.version = meta.version
    j.data_encoding = meta.data_encoding
    r = db.search_blocks(breq)  # must not raise / stage an empty batch
    assert r.metrics.inspected_blocks == 0


def test_block_meta_search_geometry_survives_roundtrip(tmp_path):
    """search_entries_per_page / search_kv_per_entry are dataclass fields
    now — they must survive the meta.json round-trip (ADVICE r2 item 1)."""
    db = _db(tmp_path)
    meta, _ = _ingest(db, "t1", 4)
    raw = db.backend.read_block_meta("t1", meta.block_id)
    assert raw.search_entries_per_page > 0
    assert raw.search_kv_per_entry > 0
    assert raw.search_pages == meta.search_pages


def test_streaming_completion_bounded_memory(tmp_path):
    """complete_block of a WAL block ≫ flush size streams the output
    through backend.append (like compaction already does): peak RSS stays
    far below the output block size and the block reads back identically
    to the fully-buffered path (VERDICT r2 #6)."""
    import os
    import resource

    def build_and_complete(root, flush):
        be = LocalBackend(str(root / "blocks"))
        db = TempoDB(be, str(root / "wal"),
                     TempoDBConfig(block_encoding="none",
                                   block_page_size=32 << 10,
                                   complete_flush_bytes=flush))
        blk = db.wal.new_block("t1", data_encoding="v1")
        for i in range(120):
            oid = i.to_bytes(2, "big") * 8
            blk.append(oid, os.urandom(64 << 10), 0, 0)  # 64 KiB objects
        meta = db.complete_block(blk)
        blk.clear()
        return be, db, meta

    flush = 256 << 10  # 256 KiB flush vs ~7.5 MiB of output
    rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    be1, db1, m1 = build_and_complete(tmp_path / "stream", flush)
    rss_after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    total_out = m1.size
    assert total_out > 8 * flush
    # ru_maxrss is KiB on linux; generous allocator slack, but far below
    # the full output block that the pre-fix path buffered in RAM
    assert (rss_after - rss_before) * 1024 < total_out // 2, (
        rss_before, rss_after, total_out)

    be2, db2, m2 = build_and_complete(tmp_path / "buffered", 1 << 40)
    assert m1.total_objects == m2.total_objects == 120
    # spot-check content via find on the streamed block
    oid = (7).to_bytes(2, "big") * 8
    obj, failed = db1.find_trace_by_id("t1", oid)
    assert failed == 0 and obj is not None and len(obj) == 64 << 10


def test_truncated_entries_surface_in_search_response(tmp_path):
    """Write-time kv-slot truncation must surface on the search response
    metrics (where the operator running the possibly-falsified query sees
    it), not only in a write-time Prometheus counter (VERDICT r2 weak #7)."""
    from tempo_tpu.search.columnar import PageGeometry

    db = _db(tmp_path, search_geometry=PageGeometry(kv_per_entry=2))
    meta, traces = _ingest(db, "t1", 8)
    db.poll()
    req = _mk_req({})
    req.limit = 100
    res = db.search("t1", req)
    resp = res.response()
    # make_trace fabricates well over 2 distinct kv pairs per trace
    assert resp.metrics.truncated_entries > 0
    # splitting the same block into page-range jobs must not double count
    from tempo_tpu import tempopb
    total = resp.metrics.truncated_entries
    breq = tempopb.SearchBlocksRequest()
    breq.tenant_id = "t1"
    breq.search_req.CopyFrom(req)
    hdr = db._search_block_for(meta).header()
    for sp in range(hdr["n_pages"]):
        j = breq.jobs.add()
        j.block_id = meta.block_id
        j.start_page = sp
        j.pages_to_search = 1
    res2 = db.search_blocks(breq)
    assert res2.response().metrics.truncated_entries == total


def test_host_tier_survives_hbm_eviction(tmp_path):
    """An HBM-evicted batch must re-stage from the host-RAM stacked tier
    (one H2D copy) without re-reading or re-decompressing from the
    object store (VERDICT r3 #2)."""
    from tempo_tpu.observability import metrics as obs

    db = _db(tmp_path)
    for b in range(3):
        _ingest(db, "t1", 4, seed_base=b * 50)
    db.poll()
    req = _mk_req({})
    req.limit = 10_000
    r1 = db.search("t1", req).response()
    assert db.batcher._host_total > 0  # host tier populated

    # count backend reads of search containers to prove no re-IO
    reads = [0]
    real_read = db.backend.read
    def counting_read(*a, **kw):
        reads[0] += 1
        return real_read(*a, **kw)
    db.backend.read = counting_read

    # evict everything from HBM, keep the host tier
    with db.batcher._lock:
        db.batcher._cache.clear()
        db.batcher._cache_total = 0
    h0 = obs.batch_cache_events.value(result="host_hit")
    r2 = db.search("t1", req).response()
    assert obs.batch_cache_events.value(result="host_hit") > h0
    assert reads[0] == 0  # no object-store IO on the evicted path
    assert ({t.trace_id for t in r1.traces}
            == {t.trace_id for t in r2.traces})
    assert r1.metrics.inspected_traces == r2.metrics.inspected_traces


def test_host_tier_budget_evicts(tmp_path):
    """The host tier honors its byte budget."""
    db = _db(tmp_path)
    for b in range(4):
        _ingest(db, "t1", 4, seed_base=b * 50)
    db.poll()
    db.batcher.max_batch_pages = 1   # one group per block
    db.batcher.host_cache_bytes = 1  # budget below any batch
    req = _mk_req({})
    req.limit = 10_000
    db.search("t1", req)
    # budget of 1 byte keeps at most one entry (evict-to-last semantics)
    assert len(db.batcher._host_cache) <= 1


def test_staging_prefetch_results_identical(tmp_path):
    """With multiple groups the one-slot staging lookahead must not
    change results or metrics vs a cold single-threaded pass."""
    db = _db(tmp_path)
    db.batcher.max_batch_pages = 8  # force several groups
    for b in range(10):
        _ingest(db, "t1", 4, seed_base=b * 30)
    db.poll()
    req = _mk_req({})
    req.limit = 10_000
    r1 = db.search("t1", req).response()
    assert len(r1.traces) == 40
    # second pass: everything cached, same answers
    r2 = db.search("t1", req).response()
    assert ({t.trace_id for t in r1.traces}
            == {t.trace_id for t in r2.traces})
    assert r1.metrics.inspected_traces == r2.metrics.inspected_traces


def test_prewarm_stages_before_first_query(tmp_path):
    """prewarm (poll-triggered) stages every group and warms the compile
    cache so the first query hits the staged-batch cache."""
    from tempo_tpu.observability import metrics as obs

    db = _db(tmp_path)
    for b in range(3):
        _ingest(db, "t1", 4, seed_base=b * 40)
    db.cfg.search_prewarm_on_poll = False
    db.poll()
    staged = db.prewarm(["t1"], background=False)
    assert staged >= 1
    h0 = obs.batch_cache_events.value(result="hit")
    req = _mk_req({})
    req.limit = 10_000
    r = db.search("t1", req).response()
    assert len(r.traces) == 12
    assert obs.batch_cache_events.value(result="hit") > h0  # no staging


# ---------------------------------------------------------------------------
# steady-state poll economics (r4): unchanged corpus must not churn memos


def test_blocklist_epoch_stable_when_poll_unchanged():
    from tempo_tpu.backend.types import BlockMeta
    from tempo_tpu.db.blocklist import Blocklist

    bl = Blocklist()
    metas = {"t1": [BlockMeta(tenant_id="t1", block_id="b1"),
                    BlockMeta(tenant_id="t1", block_id="b2")]}
    bl.apply_poll_results(metas, {"t1": []})
    e1 = bl.epoch()
    # identical content (fresh objects) -> same epoch: frontend job
    # templates and batcher plans keyed on it stay valid
    bl.apply_poll_results(
        {"t1": [BlockMeta(tenant_id="t1", block_id="b1"),
                BlockMeta(tenant_id="t1", block_id="b2")]}, {"t1": []})
    assert bl.epoch() == e1
    # real change bumps
    bl.apply_poll_results(
        {"t1": [BlockMeta(tenant_id="t1", block_id="b3")]}, {"t1": []})
    assert bl.epoch() == e1 + 1


def test_poller_reader_dedupes_index_parse(tmp_backend_dir):
    import time as _t

    from tempo_tpu.backend import LocalBackend
    from tempo_tpu.backend.types import (BlockMeta, TenantIndex,
                                         NAME_TENANT_INDEX)
    from tempo_tpu.db.poller import Poller

    be = LocalBackend(tmp_backend_dir)
    metas = [BlockMeta(tenant_id="t1", block_id=f"b{i}") for i in range(5)]

    def write_index(ts):
        be.write("t1", None, NAME_TENANT_INDEX,
                 TenantIndex(created_at=ts, metas=metas).to_bytes())

    write_index(int(_t.time()))
    reader = Poller(be, build_index=False)
    m1, _ = reader.poll_tenant("t1")
    # builder heartbeat: same CONTENT, new created_at → the reader must
    # reuse its PARSE (same meta objects inside a fresh list — callers
    # may sort their copy without corrupting the cache)
    write_index(int(_t.time()) + 1)
    m2, _ = reader.poll_tenant("t1")
    assert m2 is not m1 and m2[0] is m1[0], "unchanged index re-parsed"
    # a consumer mutating its returned list must not poison the cache
    m2.clear()
    m2b, _ = reader.poll_tenant("t1")
    assert len(m2b) == 5
    # content change invalidates
    metas.append(BlockMeta(tenant_id="t1", block_id="b-new"))
    write_index(int(_t.time()) + 2)
    m3, _ = reader.poll_tenant("t1")
    assert m3[0] is not None and len(m3) == 6


def test_poller_staleness_honored_with_cached_content(tmp_backend_dir):
    import time as _t

    from tempo_tpu.backend import LocalBackend
    from tempo_tpu.backend.types import (BlockMeta, TenantIndex,
                                         NAME_TENANT_INDEX)
    from tempo_tpu.db.poller import Poller

    be = LocalBackend(tmp_backend_dir)
    # ONE meta object reused across writes: BlockMeta() takes a random
    # block id, and differing content would turn the second read into a
    # cache MISS — the point is the cache-HIT + stale-heartbeat path
    meta = BlockMeta(tenant_id="t1", block_id="b-fixed")
    be.write("t1", None, NAME_TENANT_INDEX,
             TenantIndex(created_at=int(_t.time()),
                         metas=[meta]).to_bytes())
    reader = Poller(be, build_index=False, stale_index_s=60)
    assert reader._read_index("t1") is not None
    # a DEAD builder: created_at stops advancing; even with the content
    # cached (same digest), staleness must still trip — the heartbeat
    # rides the document head, not the parse
    be.write("t1", None, NAME_TENANT_INDEX,
             TenantIndex(created_at=int(_t.time()) - 3600,
                         metas=[meta]).to_bytes())
    assert reader._read_index("t1") is None


def test_tenant_index_head_format_pinned():
    """The reader's head regex is byte-coupled to TenantIndex.to_bytes;
    a serializer change must fail HERE, not silently disable the
    re-parse dedupe."""
    import gzip as _gzip

    from tempo_tpu.backend.types import BlockMeta, TenantIndex
    from tempo_tpu.db.poller import INDEX_HEAD_RE

    b = TenantIndex(created_at=42,
                    metas=[BlockMeta(tenant_id="t")]).to_bytes()
    m = INDEX_HEAD_RE.match(_gzip.decompress(b)[:128])
    assert m is not None, "index head no longer matches the reader regex"
    assert int(m.group(2)) == 42


def test_poller_torn_index_falls_back(tmp_backend_dir):
    from tempo_tpu.backend import LocalBackend
    from tempo_tpu.backend.types import (BlockMeta, TenantIndex,
                                         NAME_TENANT_INDEX)
    from tempo_tpu.db.poller import Poller

    be = LocalBackend(tmp_backend_dir)
    good = TenantIndex(created_at=1,
                       metas=[BlockMeta(tenant_id="t1")]).to_bytes()
    be.write("t1", None, NAME_TENANT_INDEX, good[:-8])  # torn gzip tail
    reader = Poller(be, build_index=False)
    assert reader._read_index("t1") is None  # graceful, not EOFError
    m, c = reader.poll_tenant("t1")  # falls back to direct block poll
    assert m == [] and c == []


def test_serving_path_randomized_differential(tmp_path):
    """End-to-end fuzz: random traces across several blocks, random
    predicates, `TempoDB.search` must return exactly the proto-oracle
    match set — extraction, container build, batch planning, staging,
    kernel, and merge all in the loop."""
    import random as _random

    from tempo_tpu.model.matches import matches as proto_matches

    rng = _random.Random(77)
    be = LocalBackend(str(tmp_path / "be"))
    db = TempoDB(be, str(tmp_path / "wal"),
                 TempoDBConfig(compaction_window_s=10**10,
                               retention_s=10**10))
    codec = codec_for("v2")
    traces = {}
    for blk in range(4):
        objs, search_entries = [], []
        for i in range(rng.randint(5, 40)):
            tid = random_trace_id()
            tr = make_trace(tid, seed=rng.randint(0, 10**6))
            traces[tid] = tr
            from tempo_tpu.model.matches import trace_range_ns
            s_ns, e_ns = trace_range_ns(tr)
            objs.append((tid, codec.marshal(tr, s_ns // 10**9, e_ns // 10**9),
                         s_ns // 10**9, e_ns // 10**9))
            search_entries.append(extract_search_data(tid, tr))
        order = sorted(range(len(objs)), key=lambda k: objs[k][0])
        db.write_block_direct(
            "t1", [objs[k] for k in order],
            search_entries=[search_entries[k] for k in order])
    db.poll()

    from tests.test_search import _mk_req
    for round_ in range(12):
        tags = {}
        for _ in range(rng.randint(0, 2)):
            k = rng.choice(["service.name", "component", "http.status_code",
                            "region"])
            tags[k] = rng.choice(["front", "db", "cart", "5", "us", "zz-no"])
        kw = {}
        if rng.random() < 0.4:
            kw["min_duration_ms"] = rng.choice([1, 1000, 20_000])
        if rng.random() < 0.4:
            kw["max_duration_ms"] = rng.choice([500, 30_000])
        req = _mk_req(tags, **kw)
        req.limit = 10_000
        expected = {tid.hex() for tid, tr in traces.items()
                    if proto_matches(tr, req)}
        got = {m.trace_id for m in db.search("t1", req).response().traces}
        assert got == expected, (round_, tags, kw,
                                 len(got), len(expected))


# ---------------------------------------------------------------------------
# restartable host state (VERDICT r4 #3)


def test_header_snapshot_restart_skips_backend_reads(tmp_path):
    """A restarted process (same wal dir) loads header rollups from the
    snapshot: first-query job planning costs ZERO backend header reads."""
    from tempo_tpu.backend.types import NAME_SEARCH_HEADER
    from tests.test_search import _mk_req

    db = _db(tmp_path)
    _ingest(db, "t1", 6)
    db.poll()
    req = _mk_req({})
    req.limit = 10
    db.search("t1", req)        # populates the header cache lazily
    db.save_host_state()
    assert (tmp_path / "wal" / "host-state"
            / "search-headers.json.gz").exists()

    reads = []
    be = LocalBackend(str(tmp_path / "blocks"))
    orig = be.read

    def counting_read(tenant, block_id, name):
        reads.append(name)
        return orig(tenant, block_id, name)

    be.read = counting_read
    db2 = TempoDB(be, str(tmp_path / "wal"), TempoDBConfig())
    db2.poll()
    r = db2.search("t1", req)
    assert r.metrics.inspected_blocks >= 1
    assert NAME_SEARCH_HEADER not in reads, (
        "restart re-read block headers despite the snapshot")


def test_header_snapshot_corrupt_is_ignored(tmp_path):
    db = _db(tmp_path)
    _ingest(db, "t1", 3)
    db.poll()
    snap = tmp_path / "wal" / "host-state" / "search-headers.json.gz"
    snap.parent.mkdir(parents=True, exist_ok=True)
    snap.write_bytes(b"\x1f\x8bgarbage-not-gzip")
    db2 = _db(tmp_path)   # must not raise
    db2.poll()
    from tests.test_search import _mk_req
    req = _mk_req({})
    req.limit = 10
    assert db2.search("t1", req).metrics.inspected_blocks >= 1


def test_host_state_opt_out(tmp_path):
    db = _db(tmp_path, host_state_dir="")
    _ingest(db, "t1", 2)
    db.poll()
    assert not (tmp_path / "wal" / "host-state").exists()


def test_compile_cache_dir_configured(tmp_path):
    # subprocess: jax's compilation-cache config is process-global and
    # FIRST-wins (explicit env beats per-TempoDB defaults), so an
    # in-process assert would see whichever test ran first
    import subprocess
    import sys

    code = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "from tempo_tpu.db import TempoDB, TempoDBConfig\n"
        "from tempo_tpu.backend import LocalBackend\n"
        f"TempoDB(LocalBackend({str(tmp_path / 'blocks')!r}),"
        f" {str(tmp_path / 'wal')!r}, TempoDBConfig())\n"
        "print(jax.config.jax_compilation_cache_dir)\n"
    )
    env = dict(__import__('os').environ)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    want = str(tmp_path / "wal" / "host-state" / "xla-cache")
    assert out.stdout.strip().endswith(want), (out.stdout, out.stderr[-500:])
    import os as _os
    assert _os.path.isdir(want)
