"""Ops asset validation: dashboards/alerts parse and reference only
metrics the code actually exposes (the mixin must not drift from
observability/metrics.py — reference tempo-mixin keys dashboards to its
metric namespaces the same way)."""

from __future__ import annotations

import json
import os
import re

import pytest
import yaml

OPS = os.path.join(os.path.dirname(__file__), "..", "operations")

_METRIC_RE = re.compile(r"\b(tempo[a-z_]*_[a-z_]+|traces_[a-z_]+)\b")


def _exposed_metric_names() -> set[str]:
    import tempo_tpu.api.kafka  # noqa: F401 — registers its counters
    import tempo_tpu.modules.membership  # noqa: F401
    import tempo_tpu.modules.worker  # noqa: F401 — pull-dispatch metrics
    import tempo_tpu.modules.generator as gen
    from tempo_tpu.observability.metrics import REGISTRY, Registry

    names = set(REGISTRY._metrics)
    # generator metrics live in per-instance registries
    g = gen.SpanMetricsProcessor(Registry())
    sg = gen.ServiceGraphProcessor(Registry())
    for proc in (g, sg):
        for attr in vars(proc).values():
            if hasattr(attr, "name") and isinstance(getattr(attr, "name"), str):
                names.add(attr.name)
    # cache metrics
    import tempo_tpu.backend.netcache  # noqa: F401
    import tempo_tpu.backend.cache  # noqa: F401
    names |= set(REGISTRY._metrics)
    return names


def _referenced(text: str) -> set[str]:
    out = set()
    for m in _METRIC_RE.findall(text):
        # strip histogram suffixes to the base series name
        base = re.sub(r"_(bucket|sum|count)$", "", m)
        out.add(base)
    return out


def test_dashboards_parse_and_reference_real_metrics():
    ddir = os.path.join(OPS, "tempo-mixin", "dashboards")
    exposed = _exposed_metric_names()
    checked = 0
    for name in sorted(os.listdir(ddir)):
        with open(os.path.join(ddir, name)) as f:
            dash = json.load(f)
        assert dash["title"].startswith("Tempo-TPU")
        for panel in dash["panels"]:
            assert panel.get("type") in ("timeseries", "stat")
            for tgt in panel.get("targets", []):
                for metric in _referenced(tgt["expr"]):
                    assert metric in exposed, (name, panel["title"], metric)
                    checked += 1
    assert checked > 10


def test_alert_rules_parse_and_reference_real_metrics():
    with open(os.path.join(OPS, "tempo-mixin", "alerts.yaml")) as f:
        doc = yaml.safe_load(f)
    exposed = _exposed_metric_names()
    runbook = open(os.path.join(OPS, "runbook.md")).read().lower()
    n = 0
    for group in doc["groups"]:
        for rule in group["rules"]:
            assert rule["alert"] and rule["expr"]
            for metric in _referenced(rule["expr"]):
                assert metric in exposed, (rule["alert"], metric)
            anchor = rule["annotations"]["runbook"].split("#", 1)[1]
            # every alert's runbook anchor resolves to a section heading
            assert "## " + anchor.replace("-", " ") in runbook, anchor
            n += 1
    assert n >= 8


def test_kube_manifests_parse():
    kdir = os.path.join(OPS, "kube")
    kinds = []
    for name in sorted(os.listdir(kdir)):
        if not name.endswith(".yaml"):
            continue
        with open(os.path.join(kdir, name)) as f:
            for doc in yaml.safe_load_all(f):
                assert doc["apiVersion"] and doc["kind"]
                kinds.append(doc["kind"])
    assert kinds.count("Deployment") >= 3
    assert "StatefulSet" in kinds and "ConfigMap" in kinds and "Service" in kinds


def test_kube_config_loads_through_our_loader():
    """The ConfigMap's embedded tempo.yaml must parse with cli/config.py
    (env placeholders intact)."""
    from tempo_tpu.cli.config import load_config

    with open(os.path.join(OPS, "kube", "configmap.yaml")) as f:
        cm = yaml.safe_load(f)
    cfg, runtime = load_config(text=cm["data"]["tempo.yaml"])
    assert cfg.backend["backend"] == "s3"
    assert cfg.replication_factor == 3
    join = runtime["memberlist"]["join"]
    assert join and join[0].startswith("dnssrv+")
    # the dnssrv spec in the manifest is well-formed per our validator
    from tempo_tpu.utils.dns import validate_spec

    for spec in join:
        validate_spec(spec)


# ---------------------------------------------------------------------------
# chart render parity (reference operations/helm + jsonnet role)


def _chart():
    import importlib.util

    path = os.path.join(OPS, "chart", "chart.py")
    spec = importlib.util.spec_from_file_location("tempo_chart", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_chart_render_matches_checked_in_manifests():
    """operations/kube is provably a render of the chart at default
    values — any hand-edit to either side fails here (the reference's
    generated kube-manifests/ contract)."""
    chart = _chart()
    rendered = chart.render_all(chart.load_values())
    kube = os.path.join(OPS, "kube")
    for name, content in rendered.items():
        on_disk = open(os.path.join(kube, name)).read()
        assert on_disk == content, f"{name} drifted from the chart render"
    # and nothing in kube/ is outside the chart's output set (README ok)
    extra = {f for f in os.listdir(kube)
             if f.endswith(".yaml")} - set(rendered)
    assert not extra, f"hand-written manifests outside the chart: {extra}"


def test_chart_values_override(tmp_path):
    """Overlay values parameterize replicas, namespace, image, and the
    TPU pool; rendered YAML stays parseable."""
    chart = _chart()
    overlay = tmp_path / "prod.yaml"
    overlay.write_text("""
namespace: tracing-prod
image: registry.example/tempo-tpu:1.2.3
replicas: {querier: 8, ingester: 5}
querier:
  tpu: {accelerator: tpu-v5p-slice, topology: 2x2x1, chips: 4}
""")
    rendered = chart.render_all(chart.load_values(str(overlay)))
    q = list(yaml.safe_load_all(rendered["querier.yaml"]))[0]
    assert q["metadata"]["namespace"] == "tracing-prod"
    assert q["spec"]["replicas"] == 8
    tpl = q["spec"]["template"]["spec"]
    assert tpl["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x2x1"
    c = tpl["containers"][0]
    assert c["image"] == "registry.example/tempo-tpu:1.2.3"
    assert c["resources"]["limits"]["google.com/tpu"] == "4"
    ing = list(yaml.safe_load_all(rendered["ingester.yaml"]))[0]
    assert ing["spec"]["replicas"] == 5
    for name, content in rendered.items():
        assert list(yaml.safe_load_all(content)), name


def test_chart_check_mode_detects_drift(tmp_path):
    chart = _chart()
    out = tmp_path / "kube"
    assert chart.main(["--out", str(out)]) == 0
    assert chart.main(["--check", "--out", str(out)]) == 0
    (out / "querier.yaml").write_text("hand-edited: true\n")
    assert chart.main(["--check", "--out", str(out)]) == 1


@pytest.mark.slow
def test_chart_rendered_config_boots_the_real_binary(tmp_path):
    """The manifests aren't just parseable — the ConfigMap a values
    overlay renders BOOTS the CLI, ingests, and answers a search (the
    reference's integration/e2e role for its deployment configs)."""
    import json
    import socket
    import subprocess
    import sys
    import time
    import urllib.request

    from tempo_tpu.utils.ids import random_trace_id
    from tempo_tpu.utils.test_data import make_trace

    chart = _chart()
    overlay = tmp_path / "e2e.yaml"
    overlay.write_text(f"""
storage:
  backend: local
  local: {{path: {tmp_path}/blocks}}
  wal_dir: {tmp_path}/wal
  blocklist_poll_s: 1
cache: {{cache: none, addresses: []}}
ingester: {{replication_factor: 1}}
""")
    rendered = chart.render_all(chart.load_values(str(overlay)))
    cm = yaml.safe_load(rendered["configmap.yaml"])
    tempo_yaml = cm["data"]["tempo.yaml"]
    assert "s3:" not in tempo_yaml  # only the active backend rendered
    cfg_file = tmp_path / "tempo.yaml"
    cfg_file.write_text(tempo_yaml)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        http = s.getsockname()[1]
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        grpc_port = s.getsockname()[1]

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tempo_tpu.cli.main",
         f"-config.file={cfg_file}", "-target=all",
         f"-http-port={http}", f"-grpc-port={grpc_port}"],
        cwd=os.path.join(os.path.dirname(__file__), ".."), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{http}/ready", timeout=1) as r:
                    if r.status == 200:
                        break
            except Exception:
                time.sleep(0.3)
        else:
            raise TimeoutError("rendered-config binary never became ready")

        tid = random_trace_id()
        req = urllib.request.Request(
            f"http://127.0.0.1:{http}/v1/traces",
            data=make_trace(tid, seed=1).SerializeToString(),
            headers={"Content-Type": "application/x-protobuf",
                     "X-Scope-OrgID": "e2e"})
        with urllib.request.urlopen(req, timeout=5) as r:
            assert r.status == 200

        q = urllib.request.Request(
            f"http://127.0.0.1:{http}/api/search?limit=10",
            headers={"X-Scope-OrgID": "e2e"})
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            with urllib.request.urlopen(q, timeout=5) as r:
                if json.loads(r.read()).get("traces"):
                    break
            time.sleep(0.5)
        else:
            raise TimeoutError("trace never searchable via rendered config")
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
