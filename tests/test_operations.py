"""Ops asset validation: dashboards/alerts parse and reference only
metrics the code actually exposes (the mixin must not drift from
observability/metrics.py — reference tempo-mixin keys dashboards to its
metric namespaces the same way)."""

from __future__ import annotations

import json
import os
import re

import yaml

OPS = os.path.join(os.path.dirname(__file__), "..", "operations")

_METRIC_RE = re.compile(r"\b(tempo[a-z_]*_[a-z_]+|traces_[a-z_]+)\b")


def _exposed_metric_names() -> set[str]:
    import tempo_tpu.api.kafka  # noqa: F401 — registers its counters
    import tempo_tpu.modules.membership  # noqa: F401
    import tempo_tpu.modules.generator as gen
    from tempo_tpu.observability.metrics import REGISTRY, Registry

    names = set(REGISTRY._metrics)
    # generator metrics live in per-instance registries
    g = gen.SpanMetricsProcessor(Registry())
    sg = gen.ServiceGraphProcessor(Registry())
    for proc in (g, sg):
        for attr in vars(proc).values():
            if hasattr(attr, "name") and isinstance(getattr(attr, "name"), str):
                names.add(attr.name)
    # cache metrics
    import tempo_tpu.backend.netcache  # noqa: F401
    import tempo_tpu.backend.cache  # noqa: F401
    names |= set(REGISTRY._metrics)
    return names


def _referenced(text: str) -> set[str]:
    out = set()
    for m in _METRIC_RE.findall(text):
        # strip histogram suffixes to the base series name
        base = re.sub(r"_(bucket|sum|count)$", "", m)
        out.add(base)
    return out


def test_dashboards_parse_and_reference_real_metrics():
    ddir = os.path.join(OPS, "tempo-mixin", "dashboards")
    exposed = _exposed_metric_names()
    checked = 0
    for name in sorted(os.listdir(ddir)):
        with open(os.path.join(ddir, name)) as f:
            dash = json.load(f)
        assert dash["title"].startswith("Tempo-TPU")
        for panel in dash["panels"]:
            assert panel.get("type") in ("timeseries", "stat")
            for tgt in panel.get("targets", []):
                for metric in _referenced(tgt["expr"]):
                    assert metric in exposed, (name, panel["title"], metric)
                    checked += 1
    assert checked > 10


def test_alert_rules_parse_and_reference_real_metrics():
    with open(os.path.join(OPS, "tempo-mixin", "alerts.yaml")) as f:
        doc = yaml.safe_load(f)
    exposed = _exposed_metric_names()
    runbook = open(os.path.join(OPS, "runbook.md")).read().lower()
    n = 0
    for group in doc["groups"]:
        for rule in group["rules"]:
            assert rule["alert"] and rule["expr"]
            for metric in _referenced(rule["expr"]):
                assert metric in exposed, (rule["alert"], metric)
            anchor = rule["annotations"]["runbook"].split("#", 1)[1]
            # every alert's runbook anchor resolves to a section heading
            assert "## " + anchor.replace("-", " ") in runbook, anchor
            n += 1
    assert n >= 8


def test_kube_manifests_parse():
    kdir = os.path.join(OPS, "kube")
    kinds = []
    for name in sorted(os.listdir(kdir)):
        if not name.endswith(".yaml"):
            continue
        with open(os.path.join(kdir, name)) as f:
            for doc in yaml.safe_load_all(f):
                assert doc["apiVersion"] and doc["kind"]
                kinds.append(doc["kind"])
    assert kinds.count("Deployment") >= 3
    assert "StatefulSet" in kinds and "ConfigMap" in kinds and "Service" in kinds


def test_kube_config_loads_through_our_loader():
    """The ConfigMap's embedded tempo.yaml must parse with cli/config.py
    (env placeholders intact)."""
    from tempo_tpu.cli.config import load_config

    with open(os.path.join(OPS, "kube", "configmap.yaml")) as f:
        cm = yaml.safe_load(f)
    cfg, runtime = load_config(text=cm["data"]["tempo.yaml"])
    assert cfg.backend["backend"] == "s3"
    assert cfg.replication_factor == 3
    join = runtime["memberlist"]["join"]
    assert join and join[0].startswith("dnssrv+")
    # the dnssrv spec in the manifest is well-formed per our validator
    from tempo_tpu.utils.dns import validate_spec

    for spec in join:
        validate_spec(spec)
