"""In-process Kafka broker speaking the real wire protocol.

The protocol-faithful fake for receiver tests (the role minio/azurite
play for the object backends — SURVEY.md §4 "fixtures & fakes"). Serves
ApiVersions/Metadata/ListOffsets/Fetch/Produce/FindCoordinator/
OffsetCommit/OffsetFetch on a real TCP socket, stores produced
RecordBatch v2 bytes verbatim (rewriting only baseOffset, which is not
CRC-covered), so consumer-side CRC verification runs against bytes the
broker never re-encoded.
"""

from __future__ import annotations

import socketserver
import struct
import threading

from tempo_tpu.api.kafka import (
    API_API_VERSIONS,
    API_FETCH,
    API_FIND_COORDINATOR,
    API_LIST_OFFSETS,
    API_METADATA,
    API_OFFSET_COMMIT,
    API_OFFSET_FETCH,
    API_PRODUCE,
    API_SASL_AUTHENTICATE,
    API_SASL_HANDSHAKE,
    ERR_OFFSET_OUT_OF_RANGE,
    Reader,
    Writer,
    decode_record_batches,
)


class _Log:
    def __init__(self):
        self.batches: list[tuple[int, int, bytes]] = []  # (base, last, bytes)
        self.next_offset = 0
        self.start_offset = 0  # advanced by truncate() (retention)


class FakeKafkaBroker:
    def __init__(
        self,
        n_partitions: int = 2,
        topics: list[str] | None = None,
        sasl: tuple[str, str] | None = None,
    ):
        self.n_partitions = n_partitions
        self.topics = set(topics or [])
        self.sasl = sasl  # (username, password) required when set
        self.logs: dict[tuple[str, int], _Log] = {}
        self.group_offsets: dict[tuple[str, str, int], int] = {}
        self.lock = threading.Lock()
        broker = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                state = {"authed": broker.sasl is None}
                try:
                    while True:
                        hdr = self._recvn(4)
                        if hdr is None:
                            return
                        (size,) = struct.unpack(">i", hdr)
                        payload = self._recvn(size)
                        if payload is None:
                            return
                        resp = broker.dispatch(payload, state)
                        if resp is None:
                            return  # unauthenticated: drop the connection
                        self.request.sendall(struct.pack(">i", len(resp)) + resp)
                except (ConnectionError, OSError):
                    pass

            def _recvn(self, n):
                chunks = []
                while n:
                    c = self.request.recv(n)
                    if not c:
                        return None
                    chunks.append(c)
                    n -= len(c)
                return b"".join(chunks)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.server = Server(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self.server.shutdown()
        self.server.server_close()

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def _log(self, topic: str, partition: int) -> _Log:
        self.topics.add(topic)
        return self.logs.setdefault((topic, partition), _Log())

    # -- direct test helpers -------------------------------------------------

    def append(self, topic: str, partition: int, batch: bytes) -> int:
        """Store a produced batch; returns its base offset."""
        recs = decode_record_batches(batch)
        n = len(recs) or 1
        with self.lock:
            log = self._log(topic, partition)
            base = log.next_offset
            rebased = struct.pack(">q", base) + batch[8:]
            log.batches.append((base, base + n - 1, rebased))
            log.next_offset = base + n
            return base

    def truncate(self, topic: str, partition: int, new_start: int) -> None:
        """Simulate retention: delete batches wholly below new_start."""
        with self.lock:
            log = self._log(topic, partition)
            log.batches = [b for b in log.batches if b[1] >= new_start]
            log.start_offset = max(log.start_offset, new_start)

    # -- dispatch ------------------------------------------------------------

    def dispatch(self, payload: bytes, state: dict | None = None) -> bytes | None:
        state = state if state is not None else {"authed": True}
        r = Reader(payload)
        api_key = r.i16()
        api_version = r.i16()
        corr = r.i32()
        r.string()  # client id
        w = Writer()
        w.i32(corr)
        if api_key == API_SASL_HANDSHAKE:
            mech = r.string()
            w.i16(0 if mech == "PLAIN" else 33)  # UNSUPPORTED_SASL_MECHANISM
            w.i32(1)
            w.string("PLAIN")
            state["handshook"] = mech == "PLAIN"
            return w.getvalue()
        if api_key == API_SASL_AUTHENTICATE:
            auth = r.bytes_() or b""
            parts = auth.split(b"\x00")
            ok = (
                self.sasl is not None
                and state.get("handshook")
                and len(parts) == 3
                and parts[1].decode() == self.sasl[0]
                and parts[2].decode() == self.sasl[1]
            )
            w.i16(0 if ok else 58)  # SASL_AUTHENTICATION_FAILED
            w.string(None if ok else "invalid credentials")
            w.bytes_(b"")
            state["authed"] = bool(ok)
            return w.getvalue()
        if not state.get("authed"):
            return None  # real brokers kill unauthenticated connections
        handler = {
            API_API_VERSIONS: self._api_versions,
            API_METADATA: self._metadata,
            API_LIST_OFFSETS: self._list_offsets,
            API_FETCH: self._fetch,
            API_PRODUCE: self._produce,
            API_FIND_COORDINATOR: self._find_coordinator,
            API_OFFSET_COMMIT: self._offset_commit,
            API_OFFSET_FETCH: self._offset_fetch,
        }[api_key]
        handler(r, w, api_version)
        return w.getvalue()

    def _api_versions(self, r, w, v):
        keys = [
            (API_PRODUCE, 0, 3), (API_FETCH, 0, 4), (API_LIST_OFFSETS, 0, 1),
            (API_METADATA, 0, 1), (API_OFFSET_COMMIT, 0, 2), (API_OFFSET_FETCH, 0, 1),
            (API_FIND_COORDINATOR, 0, 0), (API_API_VERSIONS, 0, 0),
        ]
        w.i16(0)
        w.i32(len(keys))
        for k, lo, hi in keys:
            w.i16(k)
            w.i16(lo)
            w.i16(hi)

    def _metadata(self, r, w, v):
        n = r.i32()
        topics = [r.string() for _ in range(n)] if n >= 0 else sorted(self.topics)
        if n == 0:
            topics = sorted(self.topics)
        w.i32(1)  # brokers
        w.i32(0)  # node id
        w.string("127.0.0.1")
        w.i32(self.port)
        w.string(None)  # rack
        w.i32(0)  # controller
        w.i32(len(topics))
        for t in topics:
            w.i16(0)
            w.string(t)
            w.i8(0)  # not internal
            w.i32(self.n_partitions)
            for p in range(self.n_partitions):
                w.i16(0)
                w.i32(p)
                w.i32(0)  # leader
                w.i32(1)
                w.i32(0)  # replicas
                w.i32(1)
                w.i32(0)  # isr
            self.topics.add(t)

    def _list_offsets(self, r, w, v):
        r.i32()  # replica
        out = []
        for _ in range(r.i32()):
            topic = r.string()
            parts = []
            for _ in range(r.i32()):
                p = r.i32()
                ts = r.i64()
                with self.lock:
                    log = self._log(topic, p)
                    off = log.start_offset if ts == -2 else log.next_offset
                parts.append((p, off))
            out.append((topic, parts))
        w.i32(len(out))
        for topic, parts in out:
            w.string(topic)
            w.i32(len(parts))
            for p, off in parts:
                w.i32(p)
                w.i16(0)
                w.i64(-1)
                w.i64(off)

    def _fetch(self, r, w, v):
        r.i32()  # replica
        r.i32()  # max wait
        r.i32()  # min bytes
        r.i32()  # max bytes
        r.i8()  # isolation
        out = []
        for _ in range(r.i32()):
            topic = r.string()
            parts = []
            for _ in range(r.i32()):
                p = r.i32()
                offset = r.i64()
                r.i32()  # partition max bytes
                with self.lock:
                    log = self._log(topic, p)
                    if offset < log.start_offset or offset > log.next_offset:
                        parts.append((p, log.next_offset, None))
                        continue
                    data = b"".join(
                        b for base, last, b in log.batches if last >= offset
                    )
                    hw = log.next_offset
                parts.append((p, hw, data))
            out.append((topic, parts))
        w.i32(0)  # throttle
        w.i32(len(out))
        for topic, parts in out:
            w.string(topic)
            w.i32(len(parts))
            for p, hw, data in parts:
                w.i32(p)
                w.i16(ERR_OFFSET_OUT_OF_RANGE if data is None else 0)
                w.i64(hw)
                w.i64(hw)  # last stable
                w.i32(0)  # aborted txns
                w.bytes_(data or b"")

    def _produce(self, r, w, v):
        r.string()  # txn id
        r.i16()  # acks
        r.i32()  # timeout
        out = []
        for _ in range(r.i32()):
            topic = r.string()
            parts = []
            for _ in range(r.i32()):
                p = r.i32()
                batch = r.bytes_() or b""
                base = self.append(topic, p, batch)
                parts.append((p, base))
            out.append((topic, parts))
        w.i32(len(out))
        for topic, parts in out:
            w.string(topic)
            w.i32(len(parts))
            for p, base in parts:
                w.i32(p)
                w.i16(0)
                w.i64(base)
                w.i64(-1)  # log append time
        w.i32(0)  # throttle

    def _find_coordinator(self, r, w, v):
        r.string()  # group
        w.i16(0)
        w.i32(0)
        w.string("127.0.0.1")
        w.i32(self.port)

    def _offset_commit(self, r, w, v):
        group = r.string()
        r.i32()  # generation
        r.string()  # member
        r.i64()  # retention
        out = []
        for _ in range(r.i32()):
            topic = r.string()
            parts = []
            for _ in range(r.i32()):
                p = r.i32()
                off = r.i64()
                r.string()  # metadata
                with self.lock:
                    self.group_offsets[(group, topic, p)] = off
                parts.append(p)
            out.append((topic, parts))
        w.i32(len(out))
        for topic, parts in out:
            w.string(topic)
            w.i32(len(parts))
            for p in parts:
                w.i32(p)
                w.i16(0)

    def _offset_fetch(self, r, w, v):
        group = r.string()
        out = []
        for _ in range(r.i32()):
            topic = r.string()
            parts = []
            for _ in range(r.i32()):
                p = r.i32()
                with self.lock:
                    off = self.group_offsets.get((group, topic, p), -1)
                parts.append((p, off))
            out.append((topic, parts))
        w.i32(len(out))
        for topic, parts in out:
            w.string(topic)
            w.i32(len(parts))
            for p, off in parts:
                w.i32(p)
                w.i64(off)
                w.string(None)
                w.i16(0)
