"""Load/e2e smoke: the k6 smoke_test analog (reference integration/bench).

Sustained concurrent write + read + search against the single binary over
real HTTP, asserting error-free operation and result consistency — the
write-path/read-path/health scenario matrix of smoke_test.js, sized to
stay fast in CI.
"""

import json
import threading
import urllib.request

from tempo_tpu.modules import App, AppConfig
from tempo_tpu.api import HTTPApi, serve_http
from tempo_tpu.utils.ids import random_trace_id, trace_id_to_hex
from tempo_tpu.utils.test_data import make_trace


def test_concurrent_write_read_smoke(tmp_path):
    app = App(AppConfig(wal_dir=str(tmp_path / "wal"), n_ingesters=2,
                        replication_factor=2))
    server = serve_http(HTTPApi(app), host="127.0.0.1", port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()

    errors = []
    written = {}
    lock = threading.Lock()

    def writer(wid):
        try:
            for i in range(15):
                tid = random_trace_id()
                tr = make_trace(tid, seed=wid * 100 + i)
                app.push("smoke", list(tr.batches))
                with lock:
                    written[tid] = tr
        except Exception as e:  # noqa: BLE001
            errors.append(("write", e))

    def reader():
        try:
            for _ in range(20):
                with lock:
                    if not written:
                        continue
                    tid = next(iter(written))
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/api/traces/{trace_id_to_hex(tid)}",
                    headers={"X-Scope-OrgID": "smoke"},
                )
                with urllib.request.urlopen(req) as r:
                    assert r.status in (200, 404)
        except Exception as e:  # noqa: BLE001
            errors.append(("read", e))

    def health():
        try:
            for _ in range(10):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/ready"
                ) as r:
                    assert r.status == 200
        except Exception as e:  # noqa: BLE001
            errors.append(("health", e))

    threads = [threading.Thread(target=writer, args=(w,)) for w in range(4)]
    threads += [threading.Thread(target=reader) for _ in range(2)]
    threads.append(threading.Thread(target=health))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    server.shutdown()

    assert not errors, errors[:3]
    assert len(written) == 60

    # everything written under concurrency is findable
    missing = [t for t in written if not app.find_trace("smoke", t).trace.batches]
    assert not missing

    # ...and still findable after flush + poll through the block path
    app.flush_tick(force=True)
    app.poll_tick()
    missing = [t for t in written if not app.find_trace("smoke", t).trace.batches]
    assert not missing


def test_gzip_and_proto_negotiation(tmp_path):
    """VERDICT r4 #8: Accept-Encoding gzip compresses query responses
    (with measurable byte savings) and Accept: application/protobuf
    returns the wire message — reference frontend.go:121-127 parity."""
    import gzip as _gzip

    from tempo_tpu import tempopb

    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    server = serve_http(HTTPApi(app), host="127.0.0.1", port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        tids = [random_trace_id() for _ in range(20)]
        for i, tid in enumerate(tids):
            app.push("neg", list(make_trace(tid, seed=i).batches))
        app.flush_tick(force=True)
        app.poll_tick()
        base = f"http://127.0.0.1:{port}/api/search?limit=50"

        def fetch(headers):
            req = urllib.request.Request(
                base, headers={"X-Scope-OrgID": "neg", **headers})
            with urllib.request.urlopen(req) as r:
                return r.status, dict(r.headers), r.read()

        # plain JSON
        st, hdrs, plain = fetch({})
        assert st == 200 and hdrs.get("Content-Encoding") is None
        assert len(json.loads(plain)["traces"]) == 20

        # gzip: decodes to the same JSON, on-wire bytes shrink
        st, hdrs, gz = fetch({"Accept-Encoding": "gzip"})
        assert st == 200 and hdrs["Content-Encoding"] == "gzip"
        assert len(gz) < len(plain) // 2, (len(gz), len(plain))
        assert json.loads(_gzip.decompress(gz)) == json.loads(plain)

        # protobuf negotiation: parseable SearchResponse, same traces
        st, hdrs, pb = fetch({"Accept": "application/protobuf"})
        assert st == 200
        assert hdrs["Content-Type"] == "application/protobuf"
        resp = tempopb.SearchResponse()
        resp.ParseFromString(pb)
        assert len(resp.traces) == 20

        # both: gzipped protobuf
        st, hdrs, gzpb = fetch({"Accept": "application/protobuf",
                                "Accept-Encoding": "gzip"})
        assert hdrs["Content-Encoding"] == "gzip"
        resp2 = tempopb.SearchResponse()
        resp2.ParseFromString(_gzip.decompress(gzpb))
        assert len(resp2.traces) == 20

        # trace-by-id proto
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/traces/{trace_id_to_hex(tids[0])}",
            headers={"X-Scope-OrgID": "neg",
                     "Accept": "application/protobuf"})
        with urllib.request.urlopen(req) as r:
            tr = tempopb.Trace()
            tr.ParseFromString(r.read())
            assert tr.batches
    finally:
        server.shutdown()
        app.shutdown()


def test_gzip_refused_with_q0(tmp_path):
    """`Accept-Encoding: gzip;q=0` is an explicit refusal (RFC 9110) —
    the body must come back uncompressed."""
    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    server = serve_http(HTTPApi(app), host="127.0.0.1", port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        for i in range(10):
            app.push("neg", list(make_trace(random_trace_id(),
                                            seed=i).batches))
        app.flush_tick(force=True)
        app.poll_tick()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/search?limit=50",
            headers={"X-Scope-OrgID": "neg",
                     "Accept-Encoding": "gzip;q=0, identity"})
        with urllib.request.urlopen(req) as r:
            assert r.headers.get("Content-Encoding") is None
            assert "Accept-Encoding" in (r.headers.get("Vary") or "")
            json.loads(r.read())  # plain JSON
    finally:
        server.shutdown()
        app.shutdown()
