"""Packed HBM residency (tempo_tpu/search/packing.py).

The tentpole contract (docs/search-packed-residency.md): staged
value-id columns narrow to the width the per-block dictionary
cardinality allows (4-bit/uint8/uint16/uint32 codes), durations
quantize to uint16 buckets with an exact residual check at bucket
boundaries, device-probe hit masks bit-pack to uint32 words — and the
kernels unpack in-register behind a static width descriptor, so

  - `search_packed_residency: true` is byte-identical to false across
    every engine path (single, batched, coalesced, mesh-sharded,
    distributed) and the dict-probe mask-lookup path;
  - the disabled path is a true noop: legacy layout, widths None,
    logical == physical accounting;
  - physical staged bytes strictly shrink on width-winning corpora,
    and the logical/physical split is visible in the batcher totals
    and the per-query stats.

Cardinalities deliberately straddle every width boundary (15/16/17,
255/256/257, 65535/65536/65537) and durations sit on quantization
bucket edges — the places an off-by-one in the code shift or the
boundary-residual logic would first go wrong.
"""

import numpy as np
import pytest

from tempo_tpu import tempopb
from tempo_tpu.search import packing, pipeline
from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
from tempo_tpu.search.data import SearchData
from tempo_tpu.search.multiblock import (
    MultiBlockEngine,
    compile_multi,
    stack_host,
    stack_queries,
)


@pytest.fixture(autouse=True)
def _packing_off_and_cold_cache():
    """Each test flips the process-wide gate itself; leave the process
    exactly as found (gate off) and keep compile-cache products from
    one gate state out of the next test's assertions."""
    packing.configure(enabled=False)
    pipeline._COMPILE_CACHE.clear()
    yield
    packing.configure(enabled=False)
    pipeline._COMPILE_CACHE.clear()


def _corpus(n, n_vals, seed, dur_max=50_000, E=64, extra_durs=(),
            n_tags=2):
    rng = np.random.default_rng(seed)
    durs = list(rng.integers(0, dur_max, size=n).tolist())
    for i, d in enumerate(extra_durs):
        durs[i % n] = int(d)
    entries = []
    for i in range(n):
        sd = SearchData(
            trace_id=rng.bytes(16),
            start_s=int(rng.integers(1, 2_000)),
            end_s=int(rng.integers(2_000, 4_000)),
            dur_ms=durs[i],
        )
        sd.kvs = {
            "service.name": {f"svc-{int(rng.integers(0, n_vals)):07d}"},
            "http.path": {f"/p/{int(rng.integers(0, n_vals)):07d}"},
        }
        for t in range(2, n_tags):
            sd.kvs[f"tag{t}"] = {
                f"t{t}-{int(rng.integers(0, n_vals)):07d}"}
        entries.append(sd)
    return ColumnarPages.build(entries, PageGeometry(E, 64))


def _req(tags=None, **kw):
    req = tempopb.SearchRequest()
    for k, v in (tags or {}).items():
        req.tags[k] = v
    for k, v in kw.items():
        setattr(req, k, v)
    return req


def _canon(out):
    count, inspected, scores, idx = out
    return (int(count), int(inspected),
            np.asarray(scores).tolist(), np.asarray(idx).tolist())


# ---------------------------------------------------------------------------
# width selection + host-side pack/unpack units


def test_width_boundaries_straddle_exactly():
    # n values need n+1 codes (pad reserves 0), so 16/256/65536 tip over
    assert [packing.width_for_cardinality(n) for n in (15, 16, 17)] \
        == ["u4", "u8", "u8"]
    assert [packing.width_for_cardinality(n) for n in (255, 256, 257)] \
        == ["u8", "u16", "u16"]
    assert [packing.width_for_cardinality(n)
            for n in (65535, 65536, 65537)] == ["u16", "u32", "u32"]


def test_dur_width_rule():
    assert packing.dur_width(0xFFFF) == "u16"
    assert packing.dur_width(0x10000) == "q1"
    assert packing.dur_width((1 << 24) - 1) == "q8"   # residual uint8
    assert packing.dur_width(0xFFFFFFFF) == "q16"


def test_pack_unpack_ids_roundtrip_all_widths():
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    for w, n in (("u4", 15), ("u8", 255), ("u16", 65_535), ("u32", 70_000)):
        ids = rng.integers(-1, n, size=(3, 5, 8), dtype=np.int64) \
            .astype(np.int32)
        packed = packing.pack_ids_array(ids, w)
        back = np.asarray(packing.unpack_ids(jnp.asarray(packed), w))
        assert np.array_equal(back, ids), w
        # the packed format really is narrower where it should be
        if w == "u4":
            assert packed.nbytes == ids.nbytes // 8


def test_duration_ok_exact_on_bucket_edges():
    """Property: quantized-bucket + boundary-residual compare ==
    exact uint32 range compare, including bounds and durations sitting
    exactly ON bucket edges."""
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    for s in (1, 5, 8, 11, 16):
        dw = f"q{s}"
        top = min(1 << 32, 1 << (16 + s))
        dur = rng.integers(0, top, size=256, dtype=np.int64)
        edges = []
        for m in (0, 1, 2, 7, 100):
            for d in (-1, 0, 1):
                edges.append((m << s) + d)
        dur = np.concatenate([
            dur, np.clip(np.array(edges, dtype=np.int64), 0, top - 1)
        ]).astype(np.uint32)
        q, r = packing.pack_duration(dur, dw)
        assert q.dtype == np.uint16
        assert r.dtype == (np.uint8 if s <= 8 else np.uint16)
        bounds = [(0, 0xFFFFFFFF), (1 << s, (3 << s) - 1),
                  ((1 << s) + 1, 3 << s), (5, 5), ((2 << s) - 1, 2 << s)]
        for _ in range(4):
            lo, hi = sorted(rng.integers(0, top, size=2).tolist())
            bounds.append((lo, hi))
        for lo, hi in bounds:
            got = np.asarray(packing.duration_ok(
                jnp.asarray(q), jnp.asarray(r),
                jnp.uint32(lo), jnp.uint32(hi), dw))
            want = (dur >= np.uint32(lo)) & (dur <= np.uint32(hi))
            assert np.array_equal(got, want), (s, lo, hi)


def test_mask_words_roundtrip():
    import jax.numpy as jnp

    from tempo_tpu.search import dict_probe

    rng = np.random.default_rng(5)
    hits = rng.random((3, 130)) < 0.3
    words = np.asarray(packing.pack_mask_words(jnp.asarray(hits)))
    assert words.dtype == np.uint32 and words.shape == (3, 5)
    back = packing.unpack_mask_words(words, 130)
    assert np.array_equal(back, hits)
    # hits_to_ids accepts both formats
    for t in range(3):
        assert dict_probe.hits_to_ids(words[t]).tolist() \
            == np.nonzero(hits[t])[0].tolist()


# ---------------------------------------------------------------------------
# noop contract: gate off = the legacy layout exactly


def test_disabled_gate_keeps_legacy_layout():
    blocks = [_corpus(100, 200, 1), _corpus(100, 14, 2)]
    host = stack_host(blocks, pad_to=8)
    assert host.widths is None
    assert "entry_dur_res" not in host.cat
    assert host.cat["kv_key"].dtype == np.int8
    assert host.cat["kv_val"].dtype == np.int16   # 200 vals > 127
    assert host.cat["entry_dur"].dtype == np.uint32
    # logical == physical when nothing is packed
    assert host.cat_logical_nbytes == host.cat_nbytes


def test_single_block_fast_path_serves_views():
    """One block already matching the bucket shape skips the
    concatenate+pad copy: the fixed-width columns are served as views
    of the block's own arrays."""
    b = _corpus(64, 300, 3)  # 1 page of 64 entries: bucket-exact
    host = stack_host([b], pad_to=b.n_pages)
    assert np.shares_memory(host.cat["entry_start"], b.entry_start)
    assert np.shares_memory(host.cat["entry_valid"], b.entry_valid)
    # a padded stack still copies (and must)
    host2 = stack_host([b], pad_to=b.n_pages + 1)
    assert not np.shares_memory(host2.cat["entry_start"], b.entry_start)


def test_packed_strictly_fewer_physical_bytes_logical_split():
    # tag-heavy corpus (the realistic shape — kv is ~70% of a batch's
    # bytes): 14 tag keys (u4), ≤ 210 distinct values (u8 vs the
    # legacy int16 narrowing), durations within uint16
    blocks = [_corpus(200, 7, 4, n_tags=14), _corpus(200, 15, 5, n_tags=14)]
    assert max(len(b.key_dict) for b in blocks) <= 15
    assert max(len(b.val_dict) for b in blocks) <= 255
    off = stack_host(blocks, pad_to=16)
    packing.configure(enabled=True)
    on = stack_host(blocks, pad_to=16)
    assert on.widths == ("u4", "u8", "u16")
    assert on.cat_nbytes < off.cat_nbytes
    # logical view reports the unpacked layout on both sides
    assert on.cat_logical_nbytes == off.cat_nbytes == off.cat_logical_nbytes
    # > 40% narrower on this corpus shape (the bench target)
    assert on.cat_nbytes < 0.6 * off.cat_nbytes, \
        (on.cat_nbytes, off.cat_nbytes)


# ---------------------------------------------------------------------------
# differential parity: packed on ≡ off, per engine path

# duration bounds sitting exactly on q-bucket edges for the >65535
# corpora (s = 5 at max_dur ~2^21)
_EDGE = 1 << 5


def _parity_blocks():
    return [
        _corpus(120, 15, 21),                       # u4 boundary low
        _corpus(120, 16, 22),
        _corpus(120, 255, 23),
        _corpus(120, 257, 24),
        _corpus(120, 300, 25, dur_max=1 << 21,      # forces q-width
                extra_durs=(3 * _EDGE - 1, 3 * _EDGE, 3 * _EDGE + 1,
                            7 * _EDGE, 0)),
    ]


def _parity_reqs():
    return [
        _req({"service.name": "svc-0000003"}, limit=20),
        _req({"http.path": "/p/000000"}, limit=500),
        _req(min_duration_ms=3 * _EDGE, max_duration_ms=7 * _EDGE,
             limit=100),
        _req(min_duration_ms=3 * _EDGE + 1, max_duration_ms=7 * _EDGE - 1,
             limit=100),
        _req({"service.name": "svc"}, min_duration_ms=1, limit=1000),
    ]


def _run_multi(eng, blocks, req):
    host = eng.stage_host(blocks)
    batch = eng.place(host)
    mq = compile_multi(blocks, req, cache_on=batch)
    if mq is None:
        return ("pruned",)
    return _canon(eng.scan(batch, mq))


def test_parity_batched_engine():
    eng = MultiBlockEngine(top_k=64)
    blocks = _parity_blocks()
    for req in _parity_reqs():
        off = _run_multi(eng, blocks, req)
        pipeline._COMPILE_CACHE.clear()
        packing.configure(enabled=True)
        on = _run_multi(eng, blocks, req)
        packing.configure(enabled=False)
        pipeline._COMPILE_CACHE.clear()
        assert on == off, req


def test_parity_single_block_engine():
    from tempo_tpu.search.engine import ScanEngine, stage
    from tempo_tpu.search.pipeline import compile_query

    eng = ScanEngine(top_k=64)
    for b in _parity_blocks():
        for req in _parity_reqs():
            cq = compile_query(b.key_dict, b.val_dict, req)
            if cq is None:
                continue
            off = _canon(eng.scan_staged(stage(b), cq))
            packing.configure(enabled=True)
            sp = stage(b)
            assert sp.widths is not None
            on = _canon(eng.scan_staged(sp, cq))
            packing.configure(enabled=False)
            assert on == off, req


def test_parity_coalesced_engine():
    from tempo_tpu.search.engine import fetch_coalesced_out

    eng = MultiBlockEngine(top_k=32)
    blocks = _parity_blocks()
    reqs = _parity_reqs()[:3]

    def run():
        host = eng.stage_host(blocks)
        batch = eng.place(host)
        mqs = [compile_multi(blocks, r, cache_on=batch) for r in reqs]
        cq = stack_queries(mqs)
        out = fetch_coalesced_out(
            eng.coalesced_scan_async(batch, cq, top_k=32))
        return (out[0].tolist(), int(out[1]),
                out[2].tolist(), out[3].tolist())

    off = run()
    pipeline._COMPILE_CACHE.clear()
    packing.configure(enabled=True)
    on = run()
    assert on == off


def test_parity_mesh_engine():
    from tempo_tpu.parallel.mesh import make_mesh

    eng = MultiBlockEngine(top_k=32, mesh=make_mesh())
    blocks = _parity_blocks()
    for req in _parity_reqs()[:3]:
        off = _run_multi(eng, blocks, req)
        pipeline._COMPILE_CACHE.clear()
        packing.configure(enabled=True)
        on = _run_multi(eng, blocks, req)
        packing.configure(enabled=False)
        pipeline._COMPILE_CACHE.clear()
        assert on == off, req


def test_parity_dist_engine():
    from tempo_tpu.parallel.dist_search import DistributedScanEngine
    from tempo_tpu.parallel.mesh import make_mesh
    from tempo_tpu.search.pipeline import compile_query

    eng = DistributedScanEngine(make_mesh(), top_k=32)
    b = _parity_blocks()[4]
    for req in _parity_reqs():
        cq = compile_query(b.key_dict, b.val_dict, req)
        if cq is None:
            continue
        off = _canon(eng.scan_staged(eng.stage(b), cq))
        packing.configure(enabled=True)
        sp = eng.stage(b)
        assert sp.widths is not None
        on = _canon(eng.scan_staged(sp, cq))
        packing.configure(enabled=False)
        assert on == off, req


def test_parity_dict_probe_mask_path():
    """The mask-lookup membership path with bit-packed hit masks must
    agree with the unpacked masks AND the pure host range path, over a
    batch mixing device-probed and host-compiled blocks."""
    from tempo_tpu.search.multiblock import stack_blocks

    rng = np.random.default_rng(31)
    big = _corpus(150, 120, 41)      # 120 distinct values >= threshold 50
    small = _corpus(150, 10, 42)     # below threshold: host range path
    blocks = [big, small]
    reqs = [_req({"service.name": "svc-00000"}, limit=200),
            _req({"service.name": f"svc-{int(rng.integers(0, 120)):07d}"},
                 limit=50),
            _req({"http.path": "/p/"}, min_duration_ms=100, limit=300)]

    def run(probe_min_vals):
        batch = stack_blocks(blocks, pad_to=16,
                             probe_min_vals=probe_min_vals)
        eng = MultiBlockEngine(top_k=64)
        outs = []
        for req in reqs:
            mq = compile_multi(blocks, req, cache_on=batch)
            if probe_min_vals:
                assert mq.val_hits is not None  # the probe path really ran
                if packing.PACKING.enabled:
                    assert packing.is_packed_mask(mq.val_hits)
            outs.append(_canon(eng.scan(batch, mq)))
            pipeline._COMPILE_CACHE.clear()
        return outs

    host_only = run(0)
    probed_off = run(50)
    packing.configure(enabled=True)
    probed_on = run(50)
    assert probed_off == host_only
    assert probed_on == host_only


def test_host_scan_parity_over_packed_host_batch():
    """The breaker/ownership host route runs the same kernel over the
    host-tier arrays — which stage the SAME packed layout — and must
    stay byte-identical to the packed device dispatch."""
    from tempo_tpu.search.batcher import host_scan

    from tempo_tpu.search.engine import resolve_top_k

    eng = MultiBlockEngine(top_k=64)
    blocks = _parity_blocks()
    packing.configure(enabled=True)
    host = eng.stage_host(blocks)
    batch = eng.place(host)
    for req in _parity_reqs()[:3]:
        mq = compile_multi(blocks, req, cache_on=batch)
        dev = _canon(eng.scan(batch, mq))
        hb = host_scan(host, mq, resolve_top_k(eng.top_k, mq.limit))
        assert _canon(hb) == dev, req


def test_compile_cache_mask_format_flip_is_a_miss():
    """A cached probe product minted under the other gate state must
    recompile, not leak the wrong mask format into an assembled batch."""
    from tempo_tpu.search.multiblock import stack_blocks

    b = _corpus(150, 120, 43)
    req = _req({"service.name": "svc-00000"}, limit=100)
    batch = stack_blocks([b], pad_to=8, probe_min_vals=50)
    mq_off = compile_multi([b], req, cache_on=batch)
    assert not packing.is_packed_mask(mq_off.val_hits)
    packing.configure(enabled=True)
    batch2 = stack_blocks([b], pad_to=8, probe_min_vals=50)
    mq_on = compile_multi([b], req, cache_on=batch2)
    assert packing.is_packed_mask(mq_on.val_hits)


# ---------------------------------------------------------------------------
# serving path end to end: TempoDB responses + accounting split


def _write_blocks(be, n_blocks):
    import json

    from tempo_tpu.backend.types import (
        BlockMeta, NAME_SEARCH, NAME_SEARCH_HEADER,
    )
    from tempo_tpu.encoding.v2.compression import compress

    metas = []
    for s in range(n_blocks):
        pages = _corpus(256, [14, 200, 300][s % 3], 100 + s, E=64)
        m = BlockMeta(tenant_id="t", encoding="none")
        blob = compress(pages.to_bytes(), "none")
        hdr = dict(pages.header)
        hdr["encoding"] = "none"
        hdr["compressed_size"] = len(blob)
        be.write("t", m.block_id, NAME_SEARCH, blob)
        be.write("t", m.block_id, NAME_SEARCH_HEADER,
                 json.dumps(hdr).encode())
        metas.append(m)
    return metas


def test_tempodb_serving_byte_identical_and_accounted(tmp_path):
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig

    be = LocalBackend(str(tmp_path / "blocks"))
    metas = _write_blocks(be, 6)
    req = _req({"service.name": "svc-0000001"}, limit=10_000)

    def serve(tag, enabled):
        db = TempoDB(be, str(tmp_path / f"wal-{tag}"), TempoDBConfig(
            auto_mesh=False, search_max_batch_pages=8,
            search_coalesce_max_queries=0, host_state_dir="",
            search_packed_residency=enabled))
        db.blocklist.update("t", add=metas)
        resp = db.search("t", req).response()
        resp.metrics.device_seconds = 0.0
        phys = db.batcher._cache_total
        logical = db.batcher._cache_logical
        return resp.SerializeToString(), phys, logical

    off, phys_off, logical_off = serve("off", False)
    on, phys_on, logical_on = serve("on", True)
    assert on == off
    assert phys_on < phys_off
    # logical totals are layout-independent; physical sits strictly
    # below them when packed (the budget totals also carry the uploaded
    # per-predicate query tables, which the logical split leaves out)
    assert logical_on == logical_off
    assert phys_on < logical_on
    # gauges publish the split
    from tempo_tpu.observability import metrics as obs

    assert obs.hbm_logical_bytes.value() == logical_on
    packing.configure(enabled=False)


def test_query_stats_staged_bytes_split(tmp_path):
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.search import query_stats

    be = LocalBackend(str(tmp_path / "blocks"))
    metas = _write_blocks(be, 3)
    db = TempoDB(be, str(tmp_path / "wal"), TempoDBConfig(
        auto_mesh=False, search_max_batch_pages=8, host_state_dir="",
        search_coalesce_max_queries=0, search_packed_residency=True))
    db.blocklist.update("t", add=metas)
    # TempoDB.search opens its own exec-scope record; read it back from
    # the registry ring like /debug/querystats does
    query_stats.configure(enabled=True)
    db.search("t", _req({"service.name": "svc"}, limit=10_000))
    d = list(query_stats.REGISTRY._ring)[-1]
    sb = d.get("staged_bytes")
    assert sb and 0 < sb["physical"] < sb["logical"]
    packing.configure(enabled=False)


# ---------------------------------------------------------------------------
# persistent compile cache knob


def test_compile_cache_knob_and_persisted_counter(tmp_path):
    import jax

    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.observability import metrics as obs

    cache_dir = tmp_path / "xla-cache"
    be = LocalBackend(str(tmp_path / "blocks"))
    # an earlier test's TempoDB may already have pinned a (still
    # usable) cache dir — enable_compile_cache deliberately keeps the
    # first working location, so clear it to exercise the knob
    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        TempoDB(be, str(tmp_path / "wal"), TempoDBConfig(
            auto_mesh=False, host_state_dir="",
            search_compile_cache_dir=str(cache_dir)))
        assert jax.config.jax_compilation_cache_dir == str(cache_dir)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
    # the monitoring listener books persistent-cache hits under
    # result=persisted (fire the event jax 0.4.x records per retrieval)
    before = obs.jit_cache_events.value(result="persisted")
    from jax import monitoring

    monitoring.record_event("/jax/compilation_cache/cache_hits")
    assert obs.jit_cache_events.value(result="persisted") == before + 1
