"""In-process DNS server speaking the real wire format over UDP.

Protocol-faithful fake for utils/dns.py tests: answers A and SRV
queries from a configured zone, emits name-compression pointers in
responses (so the parser's pointer-following is exercised), and can
attach glue A records to SRV answers in the additional section.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading

from tempo_tpu.utils.dns import TYPE_A, TYPE_SRV, _read_name


def _encode_name(name: str, offsets: dict[str, int], pos: int) -> bytes:
    """Encode with compression: reuse an earlier occurrence of any
    suffix already emitted."""
    out = b""
    labels = name.rstrip(".").split(".")
    for i in range(len(labels)):
        suffix = ".".join(labels[i:]).lower()
        if suffix in offsets:
            return out + struct.pack(">H", 0xC000 | offsets[suffix])
        if pos + len(out) < 0x3FFF:
            offsets[suffix] = pos + len(out)
        b = labels[i].encode()
        out += bytes([len(b)]) + b
    return out + b"\x00"


class FakeDNSServer:
    """zone: {("name", TYPE): [rdata, ...]} where rdata is "1.2.3.4" for
    A and (prio, weight, port, "target.name") for SRV."""

    def __init__(self, zone: dict, udp_limit: int | None = None):
        """udp_limit: UDP responses longer than this are truncated (TC
        bit set, empty answer section) like a real 512-byte-era server;
        the full answer is served over TCP on the same port."""
        self.zone = {(n.lower().rstrip("."), t): v for (n, t), v in zone.items()}
        self.queries: list[tuple[str, int]] = []
        self.tcp_queries = 0
        self.udp_limit = udp_limit
        fake = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                data, sock = self.request
                resp = fake.answer(data)
                if resp and fake.udp_limit and len(resp) > fake.udp_limit:
                    resp = fake.truncated(data)
                if resp:
                    sock.sendto(resp, self.client_address)

        class TCPHandler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    (ln,) = struct.unpack(">H", self.rfile.read(2))
                    q = self.rfile.read(ln)
                    resp = fake.answer(q)
                    fake.tcp_queries += 1
                    self.wfile.write(struct.pack(">H", len(resp)) + resp)
                except Exception:  # noqa: BLE001 — fake server
                    pass

        # UDP and TCP must share one port (real DNS); the kernel-picked
        # UDP port may have a live TCP listener — retry on a fresh port
        class _TCPServer(socketserver.ThreadingTCPServer):
            allow_reuse_address = True  # scoped: don't mutate the stdlib class

        for _ in range(20):
            self.server = socketserver.ThreadingUDPServer(
                ("127.0.0.1", 0), Handler)
            self.server.daemon_threads = True
            self.addr = self.server.server_address  # (host, port)
            try:
                self.tcp_server = _TCPServer(self.addr, TCPHandler)
                break
            except OSError:
                self.server.server_close()
        else:
            raise OSError("fake dns: no port with both UDP and TCP free")
        self.tcp_server.daemon_threads = True
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True)
        self._tcp_thread = threading.Thread(
            target=self.tcp_server.serve_forever, daemon=True)

    def start(self):
        self._thread.start()
        self._tcp_thread.start()
        return self

    def stop(self):
        self.server.shutdown()
        self.server.server_close()
        self.tcp_server.shutdown()
        self.tcp_server.server_close()

    def truncated(self, query: bytes) -> bytes:
        """TC response: original question echoed, no answers, TC bit."""
        txid = struct.unpack_from(">H", query, 0)[0]
        qname, pos = _read_name(query, 12)
        question = query[12:pos + 4]
        return struct.pack(">HHHHHH", txid, 0x8180 | 0x0200, 1, 0, 0, 0) + question

    def answer(self, query: bytes) -> bytes:
        txid, _flags, qd, *_ = struct.unpack_from(">HHHHHH", query, 0)
        qname, pos = _read_name(query, 12)
        qtype, _qclass = struct.unpack_from(">HH", query, pos)
        self.queries.append((qname.lower(), qtype))
        answers = self.zone.get((qname.lower(), qtype), [])

        # build: header + echoed question + answers (+ SRV glue)
        offsets: dict[str, int] = {}
        body = _encode_name(qname, offsets, 12)
        body += struct.pack(">HH", qtype, 1)

        def rr(name, rtype, rdata_fn):
            nonlocal body
            body_local = _encode_name(name, offsets, 12 + len(body))
            rdata = rdata_fn(12 + len(body) + len(body_local) + 10)
            body_local += struct.pack(">HHIH", rtype, 1, 5, len(rdata)) + rdata
            body += body_local

        additional: list[tuple[str, str]] = []
        for rd in answers:
            if qtype == TYPE_A:
                rr(qname, TYPE_A, lambda _pos, ip=rd: socket.inet_aton(ip))
            elif qtype == TYPE_SRV:
                prio, weight, port, target = rd

                def srv_rdata(rd_pos, p=prio, w=weight, pt=port, tg=target):
                    return struct.pack(">HHH", p, w, pt) + _encode_name(
                        tg, offsets, rd_pos + 6
                    )

                rr(qname, TYPE_SRV, srv_rdata)
                for ip in self.zone.get((target.lower().rstrip("."), TYPE_A), []):
                    additional.append((target, ip))
        for target, ip in additional:
            rr(target, TYPE_A, lambda _pos, i=ip: socket.inet_aton(i))

        rcode = 0 if answers else 3  # NXDOMAIN when empty
        header = struct.pack(
            ">HHHHHH", txid, 0x8180 | rcode, 1, len(answers), 0, len(additional)
        )
        return header + body
