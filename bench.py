"""North-star benchmark: columnar tag-scan throughput, TPU vs CPU.

Mirrors the reference's backend-search bench harness
(tempodb/search/backend_search_block_test.go:128-172, which prints MiB/s
and Mtraces/s for the FlatBuffer page scan): same corpus, same query, two
executions —

  - CPU baseline: vectorized numpy implementation of the identical
    predicate (isin membership + bincount segment-OR + filters) — a fair
    stand-in for the reference's Go columnar scan loop.
  - TPU engine: the jit scan kernel (tempo_tpu.search.engine), staged
    arrays resident in HBM, timed over repeated queries.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "traces/s", "vs_baseline": N}
vs_baseline = TPU rate / CPU rate (target: ≥10, BASELINE.json).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def build_corpus(n_entries: int, E: int = 1024, C: int = 4, seed: int = 7):
    """Synthesize ColumnarPages-shaped arrays directly (fast, numpy) —
    semantically identical to ColumnarPages.build output."""
    from tempo_tpu.search.columnar import ColumnarPages, PageGeometry

    rng = np.random.default_rng(seed)
    services = [f"svc-{i:03d}" for i in range(64)]
    statuses = ["200", "404", "500"]
    regions = ["us-east-1", "us-west-2", "eu-west-1", "ap-south-1"]
    names = [f"op-{i}" for i in range(32)]
    key_dict = sorted(["service.name", "http.status_code", "region", "name"])
    val_dict = sorted(set(services + statuses + regions + names))
    vidx = {v: i for i, v in enumerate(val_dict)}
    kidx = {k: i for i, k in enumerate(key_dict)}

    P = -(-n_entries // E)
    assert C >= 4

    svc = rng.integers(0, len(services), size=(P, E))
    st = rng.integers(0, len(statuses), size=(P, E))
    rg = rng.integers(0, len(regions), size=(P, E))
    nm = rng.integers(0, len(names), size=(P, E))
    svc_ids = np.array([vidx[s] for s in services], dtype=np.int32)[svc]
    st_ids = np.array([vidx[s] for s in statuses], dtype=np.int32)[st]
    rg_ids = np.array([vidx[s] for s in regions], dtype=np.int32)[rg]
    nm_ids = np.array([vidx[s] for s in names], dtype=np.int32)[nm]

    kv_key = np.full((P, E, C), -1, dtype=np.int32)
    kv_val = np.full((P, E, C), -1, dtype=np.int32)
    for j, (kname, vals) in enumerate((
        ("service.name", svc_ids), ("http.status_code", st_ids),
        ("region", rg_ids), ("name", nm_ids),
    )):
        kv_key[:, :, j] = kidx[kname]
        kv_val[:, :, j] = vals

    e_idx = np.arange(E, dtype=np.int32)
    entry_start = (1_600_000_000 + rng.integers(0, 86_400, size=(P, E))).astype(np.uint32)
    entry_end = entry_start + rng.integers(0, 60, size=(P, E)).astype(np.uint32)
    entry_dur = rng.integers(1, 60_000, size=(P, E)).astype(np.uint32)
    entry_valid = np.zeros((P, E), dtype=bool)
    flat_n = np.minimum(n_entries - np.arange(P) * E, E)
    entry_valid[:] = e_idx[None, :] < flat_n[:, None]

    pages = ColumnarPages(
        geometry=PageGeometry(E, C), key_dict=key_dict, val_dict=val_dict,
        kv_key=kv_key, kv_val=kv_val,
        entry_start=entry_start, entry_end=entry_end, entry_dur=entry_dur,
        entry_valid=entry_valid,
        entry_root_svc=svc_ids.astype(np.int32),
        entry_root_name=nm_ids.astype(np.int32),
        trace_ids=np.zeros((P, E, 16), dtype=np.uint8),
        n_entries=n_entries,
        header={"n_entries": n_entries, "n_pages": P, "entries_per_page": E,
                "kv_per_entry": C},
    )
    return pages


def cpu_scan(pages, cq):
    """Vectorized numpy reference scan — the CPU baseline. Same dense
    layout, same bitmap membership test as the device kernel."""
    kv_key, kv_val = pages.kv_key, pages.kv_val
    mask = pages.entry_valid.copy()
    for t in range(cq.n_terms):
        k = cq.term_keys[t]
        vals = cq.term_vals[t]
        vals = vals[vals != np.int32(2**31 - 1)]
        valm = np.isin(kv_val, vals)
        mask &= ((kv_key == k) & valm).any(axis=-1)
    mask &= (pages.entry_dur >= cq.dur_lo) & (pages.entry_dur <= cq.dur_hi)
    mask &= (pages.entry_end >= cq.win_start) & (pages.entry_start <= cq.win_end)
    return int(mask.sum())


def main():
    n_entries = int(os.environ.get("BENCH_ENTRIES", 1_000_000))
    iters = int(os.environ.get("BENCH_ITERS", 20))

    from tempo_tpu import tempopb
    from tempo_tpu.search.engine import ScanEngine, stage
    from tempo_tpu.search.pipeline import compile_query

    pages = build_corpus(n_entries)

    req = tempopb.SearchRequest()
    req.tags["service.name"] = "svc-007"
    req.tags["http.status_code"] = "500"
    req.min_duration_ms = 500
    req.limit = 20
    cq = compile_query(pages.key_dict, pages.val_dict, req)
    assert cq is not None

    # ---- CPU baseline ----
    cpu_count = cpu_scan(pages, cq)
    t0 = time.perf_counter()
    cpu_iters = max(1, min(3, iters))
    for _ in range(cpu_iters):
        cpu_scan(pages, cq)
    cpu_rate = n_entries * cpu_iters / (time.perf_counter() - t0)

    # ---- TPU engine ----
    # NOTE on timing: through the axon relay, block_until_ready returns
    # early; only a real D2H fetch synchronizes. Device execution is
    # in-order, so enqueue N kernels and fetch the last — the delta over a
    # single enqueue+fetch isolates true per-iteration device time from
    # the (relay-inflated) fetch latency.
    eng = ScanEngine(top_k=128)
    sp = stage(pages)
    count, inspected, scores, idx = eng.scan_staged(sp, cq)  # compile+warm
    assert count == cpu_count, f"device {count} != cpu {cpu_count}"

    def enqueue_n_fetch(n):
        t0 = time.perf_counter()
        for _ in range(n):
            c, _, s_, i_ = eng.scan_staged_async(sp, cq)
        _ = int(c)  # fetch of the last result waits for all prior kernels
        return time.perf_counter() - t0

    t_one = enqueue_n_fetch(1)
    t_many = enqueue_n_fetch(iters + 1)
    per_iter = max((t_many - t_one) / iters, 1e-9)
    tpu_rate = n_entries / per_iter

    import jax

    print(json.dumps({
        "metric": "columnar_tag_scan_throughput",
        "value": round(tpu_rate),
        "unit": "traces/s",
        "vs_baseline": round(tpu_rate / cpu_rate, 3),
        "detail": {
            "platform": jax.devices()[0].platform,
            "device": str(jax.devices()[0]),
            "n_entries": n_entries,
            "n_pages": pages.n_pages,
            "matches": int(count),
            "cpu_traces_per_sec": round(cpu_rate),
            "query": "service.name=svc-007 AND http.status_code=500 AND dur>=500ms",
        },
    }))


if __name__ == "__main__":
    main()
