"""North-star benchmark: columnar tag-scan throughput, TPU vs CPU.

Mirrors the reference's backend-search bench harness
(tempodb/search/backend_search_block_test.go:128-172, which prints MiB/s
and Mtraces/s for the FlatBuffer page scan): same corpus, same query, two
executions —

  - CPU baseline: vectorized numpy implementation of the identical
    predicate (isin membership + bincount segment-OR + filters) — a fair
    stand-in for the reference's Go columnar scan loop.
  - TPU engine: the jit scan kernel (tempo_tpu.search.engine), staged
    arrays resident in HBM, timed over repeated queries.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "traces/s", "vs_baseline": N}
vs_baseline = TPU rate / CPU rate (target: ≥10, BASELINE.json).

Wedge-proof harness (round 5): `python bench.py` runs a stdlib-only
ORCHESTRATOR that never touches jax itself. Each bench config runs as
`python bench.py --phase NAME` in its own subprocess with its own
deadline, checkpointing its result to BENCH_CKPT_DIR as it completes;
the final line assembles whatever finished, with explicit per-phase
errors for anything that wedged. A preflight device probe runs first
(BENCH_PREFLIGHT_ATTEMPTS, default 1 — one wedge already means the
tunnel is gone; BENCH_TIMEOUT_PROBE seconds per attempt); if the
accelerator tunnel is unhealthy the bench degrades to a clearly-marked
CPU run instead of recording silence, with the scale phases re-run at
reduced size (BENCH_DEGRADED_SCALE=0 skips them instead) so even a
degraded round records a full trajectory point.
A hung phase loses only itself — never the completed phases.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import time

import numpy as np

import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)


def build_corpus(n_entries: int, E: int = 1024, C: int = 4, seed: int = 7):
    """Synthesize ColumnarPages-shaped arrays directly (fast, numpy) —
    semantically identical to ColumnarPages.build output."""
    from tempo_tpu.search.columnar import ColumnarPages, PageGeometry

    rng = np.random.default_rng(seed)
    services = [f"svc-{i:03d}" for i in range(64)]
    statuses = ["200", "404", "500"]
    regions = ["us-east-1", "us-west-2", "eu-west-1", "ap-south-1"]
    names = [f"op-{i}" for i in range(32)]
    key_dict = sorted(["service.name", "http.status_code", "region", "name"])
    val_dict = sorted(set(services + statuses + regions + names))
    vidx = {v: i for i, v in enumerate(val_dict)}
    kidx = {k: i for i, k in enumerate(key_dict)}

    P = -(-n_entries // E)
    assert C >= 4

    svc = rng.integers(0, len(services), size=(P, E))
    st = rng.integers(0, len(statuses), size=(P, E))
    rg = rng.integers(0, len(regions), size=(P, E))
    nm = rng.integers(0, len(names), size=(P, E))
    svc_ids = np.array([vidx[s] for s in services], dtype=np.int32)[svc]
    st_ids = np.array([vidx[s] for s in statuses], dtype=np.int32)[st]
    rg_ids = np.array([vidx[s] for s in regions], dtype=np.int32)[rg]
    nm_ids = np.array([vidx[s] for s in names], dtype=np.int32)[nm]

    kv_key = np.full((P, E, C), -1, dtype=np.int32)
    kv_val = np.full((P, E, C), -1, dtype=np.int32)
    for j, (kname, vals) in enumerate((
        ("service.name", svc_ids), ("http.status_code", st_ids),
        ("region", rg_ids), ("name", nm_ids),
    )):
        kv_key[:, :, j] = kidx[kname]
        kv_val[:, :, j] = vals

    e_idx = np.arange(E, dtype=np.int32)
    entry_start = (1_600_000_000 + rng.integers(0, 86_400, size=(P, E))).astype(np.uint32)
    entry_end = entry_start + rng.integers(0, 60, size=(P, E)).astype(np.uint32)
    entry_dur = rng.integers(1, 60_000, size=(P, E)).astype(np.uint32)
    entry_valid = np.zeros((P, E), dtype=bool)
    flat_n = np.minimum(n_entries - np.arange(P) * E, E)
    entry_valid[:] = e_idx[None, :] < flat_n[:, None]

    pages = ColumnarPages(
        geometry=PageGeometry(E, C), key_dict=key_dict, val_dict=val_dict,
        kv_key=kv_key, kv_val=kv_val,
        entry_start=entry_start, entry_end=entry_end, entry_dur=entry_dur,
        entry_valid=entry_valid,
        entry_root_svc=svc_ids.astype(np.int32),
        entry_root_name=nm_ids.astype(np.int32),
        trace_ids=np.zeros((P, E, 16), dtype=np.uint8),
        n_entries=n_entries,
        header={"n_entries": n_entries, "n_pages": P, "entries_per_page": E,
                "kv_per_entry": C},
    )
    return pages


def _dispatch_count() -> float:
    """Total device kernel dispatches so far (batched serving path)."""
    from tempo_tpu.observability import metrics as obs

    return obs.scan_dispatches.value(mode="batched")


def cpu_scan(pages, cq):
    """Vectorized numpy reference scan — the CPU baseline. Same dense
    layout, same bitmap membership test as the device kernel."""
    kv_key, kv_val = pages.kv_key, pages.kv_val
    mask = pages.entry_valid.copy()
    for t in range(cq.n_terms):
        k = cq.term_keys[t]
        vals = cq.term_vals[t]
        vals = vals[vals != np.int32(2**31 - 1)]
        valm = np.isin(kv_val, vals)
        mask &= ((kv_key == k) & valm).any(axis=-1)
    mask &= (pages.entry_dur >= cq.dur_lo) & (pages.entry_dur <= cq.dur_hi)
    mask &= (pages.entry_end >= cq.win_start) & (pages.entry_start <= cq.win_end)
    return int(mask.sum())


def _timed_rate(enqueue_fn, fetch_fn, n_entries, iters):
    """Through the axon relay, block_until_ready returns early; only a real
    D2H fetch synchronizes. Device execution is in-order, so enqueue N
    kernels and fetch the last — the delta over a single enqueue+fetch
    isolates true per-iteration device time from relay fetch latency."""
    def run(n):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = enqueue_fn()
        fetch_fn(out)
        return time.perf_counter() - t0

    # adaptive: grow the batch until the measured delta clears the relay
    # noise floor, else tiny per-iter times under-resolve to garbage
    while True:
        t_one = run(1)
        t_many = run(iters + 1)
        delta = t_many - t_one
        if delta > 0.05 or iters >= 4096:
            break
        iters *= 4
    per_iter = max(delta / iters, 1e-9)
    return n_entries / per_iter


def bench_single_block(n_entries, iters):
    """Config 1+3: single corpus, 2-term AND + duration (the headline)."""
    from tempo_tpu import tempopb
    from tempo_tpu.search.engine import ScanEngine, stage
    from tempo_tpu.search.pipeline import compile_query

    pages = build_corpus(n_entries)
    req = tempopb.SearchRequest()
    req.tags["service.name"] = "svc-007"
    req.tags["http.status_code"] = "500"
    req.min_duration_ms = 500
    req.limit = 20
    cq = compile_query(pages.key_dict, pages.val_dict, req)
    assert cq is not None, "bench query pruned the corpus block"

    cpu_count = cpu_scan(pages, cq)
    t0 = time.perf_counter()
    cpu_iters = max(1, min(3, iters))
    for _ in range(cpu_iters):
        cpu_scan(pages, cq)
    cpu_rate = n_entries * cpu_iters / (time.perf_counter() - t0)

    eng = ScanEngine(top_k=128)
    sp = stage(pages)
    count, _, _, _ = eng.scan_staged(sp, cq)  # compile+warm
    assert count == cpu_count, f"device {count} != cpu {cpu_count}"
    tpu_rate = _timed_rate(lambda: eng.scan_staged_async(sp, cq),
                           lambda out: int(out[0]), n_entries, iters)

    # duration-only filter (config 3) on the same staged corpus
    dreq = tempopb.SearchRequest()
    dreq.min_duration_ms = 30_000
    dreq.limit = 20
    dcq = compile_query(pages.key_dict, pages.val_dict, dreq)
    eng.scan_staged(sp, dcq)
    dur_rate = _timed_rate(lambda: eng.scan_staged_async(sp, dcq),
                           lambda out: int(out[0]), n_entries, iters)
    return tpu_rate, cpu_rate, int(count), dur_rate


def bench_multiblock(n_blocks, entries_per_block, iters):
    """Config 2: many blocks batched into one kernel call."""
    from tempo_tpu import tempopb
    from tempo_tpu.search.multiblock import (
        MultiBlockEngine, compile_multi, stack_blocks,
    )

    blocks = [build_corpus(entries_per_block, seed=s) for s in range(n_blocks)]
    req = tempopb.SearchRequest()
    req.tags["service.name"] = "svc-007"
    req.tags["http.status_code"] = "500"
    req.limit = 20
    mq = compile_multi(blocks, req)
    assert mq is not None, "bench query pruned every block"
    batch = stack_blocks(blocks)
    eng = MultiBlockEngine(top_k=128)
    count, inspected, _, _ = eng.scan(batch, mq)
    total = n_blocks * entries_per_block
    assert inspected == total
    rate = _timed_rate(lambda: eng.scan_async(batch, mq),
                       lambda out: int(out[0]), total, iters)
    return rate, int(count)


def bench_serving(n_blocks, entries_per_block, iters):
    """Config 2 through the SERVING path: the same multi-block corpus
    written as real backend search blocks and queried via TempoDB.search —
    the production entry (frontend → querier → TempoDB), so the number
    includes per-query host compile, batch-cache lookup, kernel dispatch
    and result fetch. Also reports p50/p95 serving latency."""
    import json as _json
    import tempfile

    from tempo_tpu import tempopb
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.backend.types import (
        BlockMeta, NAME_SEARCH, NAME_SEARCH_HEADER,
    )
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.encoding.v2.compression import compress

    total = n_blocks * entries_per_block
    with tempfile.TemporaryDirectory() as td:
        be = LocalBackend(td + "/blocks")
        db = TempoDB(be, td + "/wal", TempoDBConfig())
        metas = []
        for s in range(n_blocks):
            pages = build_corpus(entries_per_block, seed=s)
            m = BlockMeta(tenant_id="bench", encoding="zstd")
            blob = compress(pages.to_bytes(), "zstd")
            hdr = dict(pages.header)
            hdr["encoding"] = "zstd"
            hdr["compressed_size"] = len(blob)
            be.write("bench", m.block_id, NAME_SEARCH, blob)
            be.write("bench", m.block_id, NAME_SEARCH_HEADER,
                     _json.dumps(hdr).encode())
            metas.append(m)
        db.blocklist.update("bench", add=metas)

        req = tempopb.SearchRequest()
        req.tags["service.name"] = "svc-007"
        req.tags["http.status_code"] = "500"
        req.limit = 20
        r = db.search("bench", req)  # warm: stage + compile
        assert r.metrics.inspected_traces == total, (
            r.metrics.inspected_traces, total)
        dispatches = db.batcher.last_dispatches

        lat = []
        for _ in range(max(3, iters)):
            t0 = time.perf_counter()
            db.search("bench", req)
            lat.append(time.perf_counter() - t0)
        lat.sort()
        p50 = lat[len(lat) // 2] * 1e3
        p95 = lat[min(len(lat) - 1, int(len(lat) * 0.95))] * 1e3
        rate = total / (sum(lat) / len(lat))
        return rate, p50, p95, dispatches


def bench_coalesced_serving(n_blocks, entries_per_block, iters,
                            concurrency=8):
    """Cross-request query coalescing through the serving path: N
    concurrent synthetic tenants issue DISTINCT predicates over the same
    device-resident block cache; dispatches landing on the same staged
    batch within the coalescing window fuse into one multi-query kernel
    launch (search/batcher.QueryCoalescer). Reports dispatches-per-
    request (target ≤ 1/2 at concurrency 8 — the whole point), the
    coalesce ratio (queries per fused launch), p50/p95 per-request
    latency, and the HBM batch-cache hit counters. Degrades gracefully
    on CPU like every phase (jax device = cpu; same code path).

    NOTE scan_dispatches semantics: with coalescing active the counter's
    mode="batched" series counts SOLO kernel launches and
    mode="coalesced" counts fused multi-query launches — a fused launch
    increments once however many requests it served. Phases that predate
    coalescing read mode="batched" only and keep their old meaning
    (serial runs flush solo)."""
    import json as _json
    import tempfile
    import threading

    from tempo_tpu import tempopb
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.backend.types import (
        BlockMeta, NAME_SEARCH, NAME_SEARCH_HEADER,
    )
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.encoding.v2.compression import compress
    from tempo_tpu.observability import metrics as obs

    total = n_blocks * entries_per_block
    with tempfile.TemporaryDirectory() as td:
        be = LocalBackend(td + "/blocks")
        db = TempoDB(be, td + "/wal", TempoDBConfig(
            # a slightly wider window than the serving default: the bench
            # models synchronized dashboard fan-out; CPU-fallback kernels
            # are slow enough that stragglers need the headroom
            search_coalesce_window_s=0.01,
            search_coalesce_max_queries=concurrency))
        metas = []
        for s in range(n_blocks):
            pages = build_corpus(entries_per_block, seed=s)
            m = BlockMeta(tenant_id="bench", encoding="none")
            blob = compress(pages.to_bytes(), "none")
            hdr = dict(pages.header)
            hdr["encoding"] = "none"
            hdr["compressed_size"] = len(blob)
            be.write("bench", m.block_id, NAME_SEARCH, blob)
            be.write("bench", m.block_id, NAME_SEARCH_HEADER,
                     _json.dumps(hdr).encode())
            metas.append(m)
        db.blocklist.update("bench", add=metas)

        def mk_req(i):
            req = tempopb.SearchRequest()
            req.tags["service.name"] = f"svc-{i:03d}"
            req.tags["http.status_code"] = "500"
            req.limit = 20
            return req

        # warm: stage to HBM + compile the solo AND fused kernel shapes
        # (the fused shape pads Q to pow2, so one warm fusion covers the
        # steady state); correctness-check against the serial path
        r = db.search("bench", mk_req(0))
        assert r.metrics.inspected_traces == total, (
            r.metrics.inspected_traces, total)
        serial = {}
        for i in range(concurrency):
            serial[i] = db.search(
                "bench", mk_req(i)).response().SerializeToString()

        barrier = threading.Barrier(concurrency)
        rounds = max(3, iters)
        lat: list[float] = []
        lat_lock = threading.Lock()
        mismatches = []

        def worker(wi, n_rounds):
            for _rnd in range(n_rounds):
                barrier.wait()  # synchronized arrival: the dashboard
                # fan-out shape (N panels firing together)
                t0 = time.perf_counter()
                got = db.search(
                    "bench", mk_req(wi)).response().SerializeToString()
                dt = time.perf_counter() - t0
                with lat_lock:
                    lat.append(dt)
                    if got != serial[wi]:
                        mismatches.append(wi)

        def launches():
            return (obs.scan_dispatches.value(mode="batched")
                    + obs.scan_dispatches.value(mode="coalesced"))

        # one synchronized warm round so the fused (Q=concurrency) kernel
        # shape compiles outside the measured window
        warm = [threading.Thread(target=worker, args=(i, 1))
                for i in range(concurrency)]
        for t in warm:
            t.start()
        for t in warm:
            t.join()
        lat.clear()

        d0 = launches()
        q0 = obs.coalesced_queries.value()
        f0 = obs.scan_dispatches.value(mode="coalesced")
        # cache counters are process-lifetime: snapshot so the reported
        # hits/evicts cover the measured rounds only, not the serial
        # correctness pass and warm round
        h0 = obs.batch_cache_events.value(result="hit")
        e0 = obs.batch_cache_events.value(result="evict")
        threads = [threading.Thread(target=worker, args=(i, rounds))
                   for i in range(concurrency)]
        t_run0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        run_s = time.perf_counter() - t_run0
        assert not mismatches, f"coalesced results diverged: {mismatches}"

        n_requests = concurrency * rounds
        dispatches = launches() - d0
        fused = obs.scan_dispatches.value(mode="coalesced") - f0
        fused_queries = obs.coalesced_queries.value() - q0
        lat.sort()
        coalescer = db.batcher.coalescer
        window_ms = (coalescer.stats()["window_ms"]
                     if coalescer is not None else 0.0)
        return {
            "blocks": n_blocks,
            "entries_per_block": entries_per_block,
            "concurrency": concurrency,
            "rounds": rounds,
            "requests": n_requests,
            "scan_dispatches": dispatches,
            "dispatches_per_request": round(dispatches / n_requests, 3),
            "coalesce_ratio": round(fused_queries / fused, 2) if fused else 0,
            "coalesce_window_ms": window_ms,
            "p50_ms": round(lat[len(lat) // 2] * 1e3, 1),
            "p95_ms": round(lat[min(len(lat) - 1,
                                    int(len(lat) * 0.95))] * 1e3, 1),
            "requests_per_sec": round(n_requests / run_s, 1),
            "hbm_cache_hits": obs.batch_cache_events.value(result="hit") - h0,
            "hbm_cache_evicts": (obs.batch_cache_events.value(result="evict")
                                 - e0),
        }


def bench_scale(n_blocks, entries_per_block, iters):
    """North-star-scale serving (BASELINE config 5 / VERDICT r2 #1): a
    10K-block blocklist driven through the production read path, with the
    O(blocks) host costs broken out.

    Scaling law (stated, not hidden): the 1B-span north star is 10K
    blocks x 100K spans; this corpus is 10K blocks x entries_per_block
    (disk/HBM-bounded), which exercises every component whose cost scales
    with BLOCK COUNT at full size — poller, blocklist, frontend job
    sharding, batch grouping, per-block query compile, result merge. The
    per-ENTRY device-scan cost scales with the separately-measured kernel
    rate (configs.multiblock traces_per_sec); full-scale p50 is
    host_ms + 1e9 / (kernel_rate x n_chips).

    Measures via TempoDB.search (querier inner path): cold-tags p50 (new
    tag-set: per-block dictionary compile runs) vs warm p50 (compile
    cache hits) — the difference IS the per-query host compile cost at
    10K blocks; and via the full HTTP->frontend->querier path (job
    sharding + batched SearchBlocksRequests + merge)."""
    import json as _json
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from tempo_tpu import tempopb
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.backend.types import (
        BlockMeta, NAME_SEARCH, NAME_SEARCH_HEADER,
    )
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.encoding.v2.compression import compress

    E = min(512, entries_per_block)
    with tempfile.TemporaryDirectory() as td:
        be = LocalBackend(td + "/blocks")

        # 16 distinct containers cycled across the block ids: block-count
        # costs are what's under test; per-block content diversity only
        # needs to defeat trivial dedup
        t0 = time.perf_counter()
        variants = []
        for s in range(16):
            pages = build_corpus(entries_per_block, E=E, seed=100 + s)
            blob = compress(pages.to_bytes(), "zstd")
            hdr = dict(pages.header)
            hdr["encoding"] = "zstd"
            hdr["compressed_size"] = len(blob)
            variants.append((blob, _json.dumps(hdr).encode(), hdr))

        def write_block(i):
            blob, hdr_bytes, hdr = variants[i % len(variants)]
            m = BlockMeta(tenant_id="bench", encoding="zstd")
            m.search_pages = hdr["n_pages"]
            m.search_size = len(blob)
            m.search_entries_per_page = hdr["entries_per_page"]
            m.search_kv_per_entry = hdr["kv_per_entry"]
            m.total_objects = hdr["n_entries"]
            be.write("bench", m.block_id, NAME_SEARCH, blob)
            be.write("bench", m.block_id, NAME_SEARCH_HEADER, hdr_bytes)
            be.write_block_meta(m)

        with ThreadPoolExecutor(16) as ex:
            list(ex.map(write_block, range(n_blocks)))
        build_s = time.perf_counter() - t0

        # host cost 1: poller over a 10K-block bucket.
        # batch cap tuned up for a single-chip 10K-block deployment: with
        # 1-page blocks the whole tenant fits a few dispatches, and each
        # dispatch pays the (relay-inflated) host sync once
        db = TempoDB(be, td + "/wal",
                     TempoDBConfig(search_max_batch_pages=16384))
        t0 = time.perf_counter()
        db.poll()
        poll_ms = (time.perf_counter() - t0) * 1e3
        n_found = len(db.blocklist.metas("bench"))
        assert n_found == n_blocks, (n_found, n_blocks)

        def mk_req(svc):
            req = tempopb.SearchRequest()
            req.tags["service.name"] = svc
            req.tags["http.status_code"] = "500"
            req.limit = 20
            return req

        total = n_blocks * entries_per_block
        # warm-up: stage all blocks to HBM + compile one tag-set
        t0 = time.perf_counter()
        r = db.search("bench", mk_req("svc-000"))
        first_query_s = time.perf_counter() - t0
        assert r.metrics.inspected_traces == total, (
            r.metrics.inspected_traces, total)
        dispatches = db.batcher.last_dispatches

        def timed(reqs):
            lat = []
            for rq in reqs:
                t0 = time.perf_counter()
                db.search("bench", rq)
                lat.append(time.perf_counter() - t0)
            lat.sort()
            return (lat[len(lat) // 2] * 1e3,
                    lat[min(len(lat) - 1, int(len(lat) * 0.95))] * 1e3)

        n = max(5, iters)
        # warm: same tags every time -> per-block compile cache hits
        warm_p50, warm_p95 = timed([mk_req("svc-001")] * n)
        # cold tags: a NEW tag-set per query -> the per-block dictionary
        # compile runs for all n_blocks on every query
        cold_p50, cold_p95 = timed([mk_req(f"svc-{2 + i:03d}") for i in range(n)])

        # full HTTP -> frontend (job shard + batch) -> querier path
        from tempo_tpu.api.http import HTTPApi
        from tempo_tpu.modules import App, AppConfig

        from tempo_tpu.modules.frontend import FrontendConfig

        app = App(AppConfig(
            backend={"backend": "local", "local": {"path": td + "/blocks"}},
            wal_dir=td + "/wal-app",
            # default auto batch sizing: one batched SearchBlocksRequest
            # per querier -> one kernel dispatch + one device sync per
            # HTTP request (VERDICT r3 #1)
            frontend=FrontendConfig()))
        app.reader_db = db  # share the staged/blocklist state
        for q in app.queriers:
            q.db = db
        app.frontend.db = db
        api = HTTPApi(app)
        # warm the http-path's own group compositions (page-range batches
        # stage separately from the whole-tenant groups above)
        api.handle("GET", "/api/search",
                   {"tags": "service.name=svc-001 http.status_code=500",
                    "limit": "20"}, {"X-Scope-OrgID": "bench"})
        http_lat = []
        d0 = _dispatch_count()
        for i in range(n):
            t0 = time.perf_counter()
            code, doc = api.handle(
                "GET", "/api/search",
                {"tags": "service.name=svc-001 http.status_code=500",
                 "limit": "20"},
                {"X-Scope-OrgID": "bench"})
            http_lat.append(time.perf_counter() - t0)
            assert code == 200, (code, doc)
        http_dispatches_per_req = (_dispatch_count() - d0) / n
        http_lat.sort()
        http_p50 = http_lat[len(http_lat) // 2] * 1e3
        http_p95 = http_lat[min(len(http_lat) - 1,
                                int(len(http_lat) * 0.95))] * 1e3

        # VERDICT r4 #3: cold RESTART against the same corpus — a brand
        # new process with the persistent XLA compile cache + header
        # snapshot (saved below) answering its first query. Same batch
        # config so the kernel shapes (and thus cache keys) match.
        db.save_host_state()
        restart = _measure_restart(td, "bench",
                                   db.cfg.search_max_batch_pages)

        return {
            **restart,
            "blocks": n_blocks,
            "entries_per_block": entries_per_block,
            "total_entries": total,
            "corpus_build_s": round(build_s, 1),
            "poll_ms": round(poll_ms, 1),
            "first_query_ms": round(first_query_s * 1e3, 1),
            "scan_dispatches": dispatches,
            "p50_ms": round(warm_p50, 1),
            "p95_ms": round(warm_p95, 1),
            "cold_tags_p50_ms": round(cold_p50, 1),
            "cold_tags_p95_ms": round(cold_p95, 1),
            "host_compile_per_query_ms": round(max(0.0, cold_p50 - warm_p50), 1),
            "distinct_dicts": 16,
            "http_path_p50_ms": round(http_p50, 1),
            "http_path_p95_ms": round(http_p95, 1),
            # VERDICT r3 #1 "done when": ~1 kernel dispatch per HTTP
            # request, residual latency = the relay sync floor
            "http_dispatches_per_request": round(http_dispatches_per_req, 2),
        }


_RESTART_CHILD = r"""
import json, os, sys, time
sys.path.insert(0, sys.argv[1])
from tempo_tpu.utils.jaxenv import honor_jax_platforms
honor_jax_platforms(required=True)
from tempo_tpu import tempopb
from tempo_tpu.backend.local import LocalBackend
from tempo_tpu.db import TempoDB, TempoDBConfig
td, tenant, batch_pages = sys.argv[2], sys.argv[3], int(sys.argv[4])
db = TempoDB(LocalBackend(td + "/blocks"), td + "/wal",
             TempoDBConfig(search_max_batch_pages=batch_pages))
t0 = time.perf_counter(); db.poll()
poll_ms = (time.perf_counter() - t0) * 1e3
req = tempopb.SearchRequest()
req.tags["service.name"] = "svc-001"
req.tags["http.status_code"] = "500"
req.limit = 20
t0 = time.perf_counter()
r = db.search(tenant, req)
q_ms = (time.perf_counter() - t0) * 1e3
print(json.dumps({"restart_poll_ms": round(poll_ms, 1),
                  "restart_first_query_ms": round(q_ms, 1),
                  "restart_inspected": r.metrics.inspected_traces}))
"""


def _measure_restart(td: str, tenant: str, batch_pages: int) -> dict:
    """First-query cost of a brand-new PROCESS over an existing corpus:
    persistent compile cache + header snapshot make this seconds, not
    the ~31 s re-pay (r4 scale_10k.first_query_ms). Returns {} on any
    child failure — the restart number is additive, never fatal."""
    import subprocess

    try:
        p = subprocess.run(
            [sys.executable, "-c", _RESTART_CHILD, _HERE, td, tenant,
             str(batch_pages)],
            capture_output=True, text=True, timeout=600)
        for line in reversed(p.stdout.strip().splitlines()):
            if line.startswith("{"):
                return json.loads(line)
        return {"restart_error": f"rc={p.returncode}: "
                                 f"{(p.stderr or '')[-300:]}"}
    except Exception as e:  # noqa: BLE001
        return {"restart_error": repr(e)}


def bench_scale_large(n_blocks, entries_per_block, iters):
    """VERDICT r3 #2: serving economics at REALISTIC block sizes (>=64K
    entries/block) with the HBM-overflow path exercised honestly.

    Three regimes measured over the same corpus:
      - prewarm: poll + background-prewarm cost (staging + compile warm),
        then the first query (which should pay neither);
      - warm: every group HBM-resident;
      - evicted: HBM budget shrunk below the working set, so every query
        re-stages groups from the host-RAM stacked tier (H2D only, no
        IO/decompress), overlapped with compute by the staging lookahead.
    """
    import json as _json
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from tempo_tpu import tempopb
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.backend.types import (
        BlockMeta, NAME_SEARCH, NAME_SEARCH_HEADER,
    )
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.encoding.v2.compression import compress

    E = 1024
    total = n_blocks * entries_per_block
    with tempfile.TemporaryDirectory() as td:
        be = LocalBackend(td + "/blocks")
        t0 = time.perf_counter()
        variants = []
        for s in range(16):
            pages = build_corpus(entries_per_block, E=E, seed=300 + s)
            blob = compress(pages.to_bytes(), "zstd")
            hdr = dict(pages.header)
            hdr["encoding"] = "zstd"
            hdr["compressed_size"] = len(blob)
            variants.append((blob, _json.dumps(hdr).encode(), hdr))

        def write_block(i):
            blob, hdr_bytes, hdr = variants[i % len(variants)]
            m = BlockMeta(tenant_id="bench", encoding="zstd")
            m.search_pages = hdr["n_pages"]
            m.search_size = len(blob)
            m.search_entries_per_page = hdr["entries_per_page"]
            m.search_kv_per_entry = hdr["kv_per_entry"]
            m.total_objects = hdr["n_entries"]
            be.write("bench", m.block_id, NAME_SEARCH, blob)
            be.write("bench", m.block_id, NAME_SEARCH_HEADER, hdr_bytes)
            be.write_block_meta(m)

        with ThreadPoolExecutor(16) as ex:
            list(ex.map(write_block, range(n_blocks)))
        build_s = time.perf_counter() - t0

        # 8192-page groups (~100-200 MB staged): the eviction quantum.
        # r4 used 32768 → 720 MB groups whose relay-bound re-stage cost
        # ~19 s; smaller groups put an evicted-group query at low
        # seconds for a few extra (async-enqueued) dispatches per query
        db = TempoDB(be, td + "/wal", TempoDBConfig(
            search_max_batch_pages=int(os.environ.get(
                "BENCH_LARGE_BATCH_PAGES", 8192)),
            search_batch_cache_bytes=13 << 30,   # v5e HBM is 16 GB
            search_host_cache_bytes=48 << 30,
        ))
        t0 = time.perf_counter()
        db.poll()
        poll_ms = (time.perf_counter() - t0) * 1e3
        assert len(db.blocklist.metas("bench")) == n_blocks

        # prewarm: stage host+HBM and warm the XLA compile cache
        t0 = time.perf_counter()
        db.prewarm(["bench"], background=False)
        prewarm_s = time.perf_counter() - t0

        def mk_req(svc):
            req = tempopb.SearchRequest()
            req.tags["service.name"] = svc
            req.tags["http.status_code"] = "500"
            req.limit = 20
            return req

        t0 = time.perf_counter()
        r = db.search("bench", mk_req("svc-001"))
        first_query_ms = (time.perf_counter() - t0) * 1e3
        assert r.metrics.inspected_traces == total, (
            r.metrics.inspected_traces, total)
        dispatches = db.batcher.last_dispatches

        def timed(reqs):
            lat = []
            for rq in reqs:
                t0 = time.perf_counter()
                db.search("bench", rq)
                lat.append(time.perf_counter() - t0)
            lat.sort()
            return (lat[len(lat) // 2] * 1e3,
                    lat[min(len(lat) - 1, int(len(lat) * 0.95))] * 1e3)

        n = max(3, iters)
        warm_p50, warm_p95 = timed([mk_req("svc-001")] * n)

        # sustained H2D bandwidth of this execution environment: through
        # the axon relay this is ~0.25 GB/s (a harness artifact; a
        # directly-attached chip streams 10-50 GB/s over PCIe/DMA) — the
        # evicted numbers below are H2D-bound and must be read against it
        import numpy as np

        import jax
        probe = np.zeros((32 << 20,), dtype=np.int32)  # 128 MB
        jax.device_put(probe).block_until_ready()  # warm the relay path
        t0 = time.perf_counter()
        jax.device_put(probe).block_until_ready()
        h2d_mbps = 128 / (time.perf_counter() - t0)

        # evicted regime: before each query evict the LRU group from HBM
        # (churn scenario: a poll displaced part of the working set); the
        # query re-stages that group from the host-RAM stacked tier —
        # one H2D copy, no IO/decompress — overlapped by the lookahead
        hbm_bytes = db.batcher._cache_total
        ev_lat = []
        ev_group_mb = 0
        for _ in range(n):
            with db.batcher._lock:
                if len(db.batcher._cache) > 1:
                    _, old = db.batcher._cache.popitem(last=False)
                    db.batcher._cache_total -= old.nbytes
                    ev_group_mb = old.nbytes / (1 << 20)
            t0 = time.perf_counter()
            db.search("bench", mk_req("svc-001"))
            ev_lat.append(time.perf_counter() - t0)
        ev_lat.sort()
        ev_p50 = ev_lat[len(ev_lat) // 2] * 1e3
        ev_p95 = ev_lat[min(len(ev_lat) - 1, int(len(ev_lat) * 0.95))] * 1e3

        return {
            "blocks": n_blocks,
            "entries_per_block": entries_per_block,
            "total_entries": total,
            "corpus_build_s": round(build_s, 1),
            "poll_ms": round(poll_ms, 1),
            "prewarm_s": round(prewarm_s, 1),
            "first_query_after_prewarm_ms": round(first_query_ms, 1),
            "scan_dispatches": dispatches,
            "hbm_working_set_mb": round(hbm_bytes / (1 << 20)),
            "host_tier_mb": round(db.batcher._host_total / (1 << 20)),
            "p50_ms": round(warm_p50, 1),
            "p95_ms": round(warm_p95, 1),
            "evicted_p50_ms": round(ev_p50, 1),
            "evicted_p95_ms": round(ev_p95, 1),
            "evicted_group_mb": round(ev_group_mb),
            "h2d_mbps": round(h2d_mbps),
        }


def bench_high_cardinality(n_entries, cardinality, iters,
                           probe_min_vals=None):
    """Config 4: substring search against a huge value dictionary. Both
    prefilter executions are measured over the same corpus and query:

      - HOST path (`dict_prefilter_ms`): native memmem / numpy scan →
        id ranges → range-compare scan kernel (the pre-PR4 pipeline);
      - DEVICE path (`device_probe_ms`): packed dictionary staged to
        HBM, rolling-window probe kernel → hit mask → mask-lookup scan
        kernel (search/dict_probe.py) — the near-data-processing move.

    Matches must be identical between the paths (asserted), and the
    scan-rate comparison re-validates the mask-lookup-vs-range-compare
    tradeoff (the ids_to_ranges gather measurement) every round instead
    of assuming it."""
    import numpy as np

    from tempo_tpu import tempopb
    from tempo_tpu.search import dict_probe
    from tempo_tpu.search.engine import ScanEngine, stage
    from tempo_tpu.search.pipeline import compile_query, pack_val_dict

    pages = build_corpus(n_entries)
    # swap the region column for a high-cardinality id attribute
    vd = [f"session-{i:08d}" for i in range(cardinality)]
    rng = np.random.default_rng(3)
    hits = rng.integers(0, cardinality, size=pages.kv_val[:, :, 2].shape)
    base = len(pages.val_dict)
    pages.val_dict = pages.val_dict + vd
    pages.kv_val[:, :, 2] = base + hits

    req = tempopb.SearchRequest()
    req.tags["region"] = "session-0000123"  # prefix → 10 matching values
    req.limit = 20
    packed = pack_val_dict(pages.val_dict)
    t0 = time.perf_counter()
    cq = compile_query(pages.key_dict, pages.val_dict, req, packed_vals=packed)
    compile_ms = (time.perf_counter() - t0) * 1e3
    assert cq is not None, (
        "high-cardinality query matched no dictionary values — "
        "BENCH_CARDINALITY must exceed ~1240 so the session prefix exists"
    )
    eng = ScanEngine(top_k=128)
    sp = stage(pages, probe_min_vals=0)  # host-path staging: no dict
    count, _, h_scores, h_idx = eng.scan_staged(sp, cq)
    rate = _timed_rate(lambda: eng.scan_staged_async(sp, cq),
                       lambda out: int(out[0]), n_entries, iters)

    # --- device-resident probe over the same staged pages ---
    probe = {"device_probe_ms": None, "device_probe_rate": None,
             "device_probe_stage_ms": None}
    mv = (dict_probe.DEVICE_PROBE_MIN_VALS if probe_min_vals is None
          else probe_min_vals)
    if 0 < mv <= len(pages.val_dict):
        t0 = time.perf_counter()
        sp.staged_dict = dict_probe.stage_val_dict(pages.val_dict,
                                                   cache_on=pages)
        for a in sp.staged_dict.device.values():
            a.block_until_ready()
        probe["device_probe_stage_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 1)

        def dev_compile():
            # fresh compile each call (no cache_on): probe dispatch +
            # the [T]-bool any_hits prune sync — the replacement for the
            # host prefilter's dict_prefilter_ms
            return compile_query(pages.key_dict, pages.val_dict, req,
                                 staged_dict=sp.staged_dict)

        cq_dev = dev_compile()  # warm: compiles the probe kernel
        t0 = time.perf_counter()
        n_probe = max(3, min(iters, 10))
        for _ in range(n_probe):
            dev_compile()
        probe["device_probe_ms"] = round(
            (time.perf_counter() - t0) / n_probe * 1e3, 1)

        d_count, _, d_scores, d_idx = eng.scan_staged(sp, cq_dev)
        assert int(d_count) == int(count), (
            f"device probe diverged: {int(d_count)} != {int(count)}")
        assert np.array_equal(np.asarray(d_scores), np.asarray(h_scores)), \
            "device-probe top-k scores diverged from host path"
        probe["device_probe_rate"] = round(_timed_rate(
            lambda: eng.scan_staged_async(sp, cq_dev),
            lambda out: int(out[0]), n_entries, iters))

        # --- offload planner calibration: feed the MEASURED host and
        # device timings from this corpus into the cost model, take its
        # decision, run the planner-routed compile end to end, and
        # assert the matches are identical either way (the planner can
        # only move time, never results). This is the detail.planner
        # calibration table: predicted vs measured per side, the
        # decision taken, and the chosen side's mispredict.
        from tempo_tpu.search import planner as planner_mod

        planner_mod.configure(enabled=True, reset=True, seed=True)
        try:
            p = planner_mod.PLANNER
            packed_dd = sp.staged_dict.packed
            dict_bytes = packed_dd.real_bytes
            staged_bytes = sp.staged_dict.nbytes
            p.observe("host_probe", compile_ms / 1e3, nbytes=dict_bytes)
            # the measured staging wall is pack (dominant at these
            # cardinalities: millions of strings copied into the byte
            # buffer) PLUS the device put; book it as pack over the real
            # dictionary bytes — stuffing it into the h2d rate would
            # inflate seconds-per-byte 10-100x (the true h2d rate arrives
            # from the seed microbenchmark / live profiler feed)
            p.observe("pack", probe["device_probe_stage_ms"] / 1e3,
                      nbytes=dict_bytes)
            p.observe("device_probe", probe["device_probe_ms"] / 1e3,
                      nbytes=staged_bytes)
            d = p.decide_probe(
                n_vals=len(pages.val_dict), dict_bytes=dict_bytes,
                resident=True, staged_bytes=staged_bytes,
                fp=packed_dd.fingerprint, site="compile")
            cq_plan = compile_query(pages.key_dict, pages.val_dict, req,
                                    packed_vals=packed,
                                    staged_dict=sp.staged_dict)
            p_count, _, p_scores, _p_idx = eng.scan_staged(sp, cq_plan)
            assert int(p_count) == int(count), (
                f"planner-routed scan diverged: {int(p_count)} != "
                f"{int(count)}")
            assert np.array_equal(np.asarray(p_scores),
                                  np.asarray(h_scores)), \
                "planner-routed top-k scores diverged from host path"
            measured = {"host": compile_ms,
                        "device": probe["device_probe_ms"]}
            predicted = {"host": round(d.predicted_host_s * 1e3, 1),
                         "device": round(d.predicted_device_s * 1e3, 1)}
            chosen_meas = measured[d.target]
            snap = p.snapshot(recent=0)
            probe["planner"] = {
                "decision": d.target,
                "took": ("device" if cq_plan.val_hits is not None
                         else "host"),
                "predicted_ms": predicted,
                "measured_ms": measured,
                "mispredict_pct": round(
                    abs(predicted[d.target] - chosen_meas)
                    / max(chosen_meas, 1e-6) * 100, 1),
                "decisions": snap["decisions"],
                "seed_ms": snap["seed_ms"],
            }
        finally:
            planner_mod.configure(enabled=False)
    return rate, int(count), compile_ms, probe


# ---------------------------------------------------------------------------
# Phase registry — each entry runs in its own subprocess via `--phase NAME`.
# Every phase reads its sizes from the same BENCH_* env knobs as before and
# returns a JSON-able dict (the shape that lands in the final detail block).
# ---------------------------------------------------------------------------

def phase_probe():
    """Preflight: prove the device answers, and measure the fixed
    device→host round-trip of the execution environment (through the
    axon relay ~65-70 ms regardless of size; on a directly-attached TPU
    it is microseconds) so serving latency reads net of the harness."""
    import jax
    import jax.numpy as jnp

    probe_fn = jax.jit(lambda x: x + 1)
    int(probe_fn(jnp.int32(1)))  # compile once; loop measures pure sync
    t0 = time.perf_counter()
    for _ in range(5):
        int(probe_fn(jnp.int32(1)))
    relay_sync_ms = (time.perf_counter() - t0) / 5 * 1e3
    return {
        "ok": True,
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "relay_sync_ms": round(relay_sync_ms, 2),
    }


def phase_single():
    n_entries = int(os.environ.get("BENCH_ENTRIES", 1_000_000))
    iters = int(os.environ.get("BENCH_ITERS", 20))
    tpu_rate, cpu_rate, matches, dur_rate = bench_single_block(n_entries, iters)
    return {
        "n_entries": n_entries,
        "tpu_traces_per_sec": round(tpu_rate),
        "cpu_traces_per_sec": round(cpu_rate),
        "matches": matches,
        "duration_only_traces_per_sec": round(dur_rate),
    }


def phase_multiblock():
    n_entries = int(os.environ.get("BENCH_ENTRIES", 1_000_000))
    iters = int(os.environ.get("BENCH_ITERS", 20))
    n_blocks = int(os.environ.get("BENCH_BLOCKS", 100))
    rate, matches = bench_multiblock(
        n_blocks, max(1024, n_entries // n_blocks), iters)
    return {"blocks": n_blocks, "traces_per_sec": round(rate),
            "matches": matches}


def phase_serving():
    n_entries = int(os.environ.get("BENCH_ENTRIES", 1_000_000))
    iters = int(os.environ.get("BENCH_ITERS", 20))
    n_blocks = int(os.environ.get("BENCH_BLOCKS", 100))
    rate, p50, p95, dispatches = bench_serving(
        n_blocks, max(1024, n_entries // n_blocks), iters)
    return {"blocks": n_blocks, "traces_per_sec": round(rate),
            "p50_ms": round(p50, 2), "p95_ms": round(p95, 2),
            "scan_dispatches": dispatches}


def _probe_min_vals_env():
    """BENCH_PROBE_MIN_VALS: override the device-probe threshold for the
    high-cardinality phases (0 disables; unset = library default)."""
    raw = os.environ.get("BENCH_PROBE_MIN_VALS")
    return int(raw) if raw not in (None, "") else None


def phase_high_cardinality():
    n_entries = int(os.environ.get("BENCH_ENTRIES", 1_000_000))
    iters = int(os.environ.get("BENCH_ITERS", 20))
    cardinality = int(os.environ.get("BENCH_CARDINALITY", 1_000_000))
    rate, matches, compile_ms, probe = bench_high_cardinality(
        n_entries, cardinality, iters, probe_min_vals=_probe_min_vals_env())
    return {"distinct_values": cardinality, "traces_per_sec": round(rate),
            "dict_prefilter_ms": round(compile_ms, 1), "matches": matches,
            **probe}


def phase_high_cardinality_full():
    # BASELINE config 4 names 10M distinct values — run the prefilter at
    # full cardinality too (the device probe scales with dictionary
    # BYTES, so full cardinality is exactly where it must be measured)
    n_entries = int(os.environ.get("BENCH_ENTRIES", 1_000_000))
    iters = int(os.environ.get("BENCH_ITERS", 20))
    cardinality = int(os.environ.get("BENCH_CARDINALITY_FULL", 10_000_000))
    if not cardinality:
        return None
    rate, matches, compile_ms, probe = bench_high_cardinality(
        n_entries, cardinality, max(3, iters // 4),
        probe_min_vals=_probe_min_vals_env())
    return {"distinct_values": cardinality, "traces_per_sec": round(rate),
            "dict_prefilter_ms": round(compile_ms, 1), "matches": matches,
            **probe}


def phase_coalesced_serving():
    n_entries = int(os.environ.get("BENCH_ENTRIES", 1_000_000))
    iters = int(os.environ.get("BENCH_ITERS", 20))
    n_blocks = int(os.environ.get("BENCH_BLOCKS", 100))
    conc = int(os.environ.get("BENCH_COALESCE_CONCURRENCY", 8))
    return bench_coalesced_serving(
        n_blocks, max(1024, n_entries // n_blocks),
        max(3, iters // 4), concurrency=conc)


def phase_profile_overhead():
    """Dispatch-profiler contract: `search_profiling_enabled: false` is
    a TRUE noop, and the enabled profiler must cost < ~2% on the
    dispatch hot path. Measures the same fully-synchronous scan loop
    with the profiler enabled vs disabled (min-of-reps, interleaved so
    clock drift cancels) and asserts the delta; the enabled run's
    per-stage aggregates ride along for detail.profile."""
    from tempo_tpu import tempopb
    from tempo_tpu.observability import profile
    from tempo_tpu.search.engine import ScanEngine, stage
    from tempo_tpu.search.pipeline import compile_query

    n_entries = int(os.environ.get("BENCH_PROFILE_ENTRIES", 65_536))
    iters = int(os.environ.get("BENCH_PROFILE_ITERS", 150))
    reps = int(os.environ.get("BENCH_PROFILE_REPS", 5))
    pages = build_corpus(n_entries)
    req = tempopb.SearchRequest()
    req.tags["service.name"] = "svc-007"
    req.tags["http.status_code"] = "500"
    req.limit = 20
    cq = compile_query(pages.key_dict, pages.val_dict, req)
    eng = ScanEngine(top_k=128)
    sp = stage(pages)
    eng.scan_staged(sp, cq)  # compile+warm

    def run_loop(n):
        t0 = time.perf_counter()
        for _ in range(n):
            eng.scan_staged(sp, cq)  # sync path: dispatch + D2H, profiled
        return time.perf_counter() - t0

    run_loop(max(1, iters // 5))  # warmup
    t_on, t_off = [], []
    try:
        for _ in range(reps):
            profile.configure(enabled=False)
            t_off.append(run_loop(iters))
            profile.configure(enabled=True)
            t_on.append(run_loop(iters))
    finally:
        profile.configure(enabled=True)
    best_on, best_off = min(t_on), min(t_off)
    ab_overhead_pct = (best_on - best_off) / best_off * 100

    # The A/B wall-clock delta above is the honest end-to-end number but
    # on a shared host its noise floor (several %) swamps a ~50us/call
    # effect. The ASSERTED bound is deterministic: time the exact record
    # protocol an enabled dispatch adds (alloc + stage timers +
    # compile_check + publish) against the noop path, and take it as a
    # fraction of the measured per-dispatch time.
    def protocol_loop(n):
        t0 = time.perf_counter()
        for i in range(n):
            with profile.dispatch("single") as rec:
                with rec.stage("build"):
                    pass
                rec.compile_check(("overhead_probe", i % 8))
                with rec.stage("execute"):
                    pass
                with rec.stage("d2h"):
                    pass
                rec.add_bytes(d2h=64)
        return time.perf_counter() - t0

    N_PROTO = 20_000
    protocol_loop(1000)  # warm
    record_us = min(protocol_loop(N_PROTO) for _ in range(3)) \
        / N_PROTO * 1e6
    profile.configure(enabled=False)
    try:
        noop_us = min(protocol_loop(N_PROTO) for _ in range(3)) \
            / N_PROTO * 1e6
    finally:
        profile.configure(enabled=True)
    dispatch_us = best_on / iters * 1e6
    overhead_pct = (record_us - noop_us) / dispatch_us * 100

    snap = profile.PROFILER.snapshot(recent=0)
    result = {
        "n_entries": n_entries,
        "iters_per_rep": iters,
        "reps": reps,
        "enabled_s": round(best_on, 4),
        "disabled_s": round(best_off, 4),
        "ab_overhead_pct": round(ab_overhead_pct, 3),
        "record_cost_us": round(record_us - noop_us, 2),
        "noop_cost_us": round(noop_us, 3),
        "dispatch_us": round(dispatch_us, 1),
        "overhead_pct": round(overhead_pct, 3),
        "within_2pct": overhead_pct < 2.0,
        "jit_cache": snap["jit_cache"],
    }
    assert overhead_pct < 2.0, (
        f"profiling record cost {record_us - noop_us:.1f}us is "
        f"{overhead_pct:.2f}% of the {dispatch_us:.0f}us dispatch — "
        "exceeds the 2% budget")
    # The wall-clock A/B delta rides a ±6% noise floor on shared CPU
    # hosts (two interleaved 150-iteration loops cannot resolve a
    # ~50us/call effect there), so its assert is OPT-IN: set
    # BENCH_PROFILE_AB_ASSERT=1 on quiet/pinned hosts to enforce it;
    # tier-1 and default bench runs keep only the deterministic
    # protocol-cost assert above.
    ab_assert = os.environ.get("BENCH_PROFILE_AB_ASSERT", "") \
        not in ("", "0")
    result["ab_assert_enabled"] = ab_assert
    if ab_assert:
        assert ab_overhead_pct < 6.0, (
            f"enabled-vs-disabled wall clock regressed "
            f"{ab_overhead_pct:.2f}% (> 6% even allowing for noise)")
    return result


def phase_query_stats_overhead():
    """Per-query inspector contract (docs/search-query-stats.md):
    `search_query_stats_enabled: false` is a TRUE noop — byte-identical
    results either way — and the enabled per-query record protocol
    (begin + contextvar activation + the typical per-group records +
    finish/publish) must cost < 2% of a query. Same shape as
    profile_overhead: the asserted bound is the deterministic protocol
    cost (the wall A/B delta rides along, informational)."""
    from tempo_tpu import tempopb
    from tempo_tpu.search import query_stats
    from tempo_tpu.search.batcher import BlockBatcher, ScanJob

    n_entries = int(os.environ.get("BENCH_QSTATS_ENTRIES", 65_536))
    iters = int(os.environ.get("BENCH_QSTATS_ITERS", 60))
    n_blocks = 4
    blocks = [build_corpus(max(1024, n_entries // n_blocks), seed=s)
              for s in range(n_blocks)]

    def mk_jobs():
        jobs = []
        for i, b in enumerate(blocks):
            hdr = dict(b.header)
            jobs.append(ScanJob(
                key=(f"qs-{i}", 0, b.n_pages),
                pages_fn=(lambda b=b: b), header=hdr,
                n_pages=b.n_pages, n_entries=hdr["n_entries"],
                geometry=(hdr["entries_per_page"], hdr["kv_per_entry"])))
        return jobs

    req = tempopb.SearchRequest()
    req.tags["service.name"] = "svc-007"
    req.tags["http.status_code"] = "500"
    req.limit = 20
    batcher = BlockBatcher()
    jobs = mk_jobs()

    def one_query(enabled: bool):
        qs = query_stats.begin("bench", req) if enabled else None
        with query_stats.activate(qs):
            res = batcher.search(jobs, req)
        if qs is not None:
            qs.finish()
        return res.response()

    query_stats.configure(enabled=True)
    warm = one_query(True)  # stage + compile
    t_on, t_off = [], []
    r_on = r_off = None
    try:
        for _ in range(3):
            query_stats.configure(enabled=False)
            t0 = time.perf_counter()
            for _ in range(iters):
                r_off = one_query(False)
            t_off.append(time.perf_counter() - t0)
            query_stats.configure(enabled=True)
            t0 = time.perf_counter()
            for _ in range(iters):
                r_on = one_query(True)
            t_on.append(time.perf_counter() - t0)
    finally:
        query_stats.configure(enabled=True)
    query_us = min(t_on) / iters * 1e6
    ab_overhead_pct = (min(t_on) - min(t_off)) / min(t_off) * 100

    # byte-identity: the disabled and enabled paths must return the
    # same traces, and the LEGACY metrics must match exactly — only the
    # stats fields may differ
    def strip(resp):
        r = tempopb.SearchResponse()
        r.CopyFrom(resp)
        r.metrics.device_seconds = 0
        r.metrics.inspected_bytes_device = 0
        r.metrics.query_stats_json = ""
        return r.SerializeToString()

    identical = strip(r_on) == strip(r_off) == strip(warm)
    assert identical, "query-stats on/off responses diverged"

    # deterministic protocol cost: the exact per-query record sequence
    # a 4-group search performs, enabled vs disabled
    def protocol_loop(n):
        t0 = time.perf_counter()
        for _ in range(n):
            qs = query_stats.begin("bench", req)
            with query_stats.activate(qs):
                inner = query_stats.current()
                if inner is not None:
                    for _g in range(4):
                        inner.add_cache("hbm_hit")
                        inner.add_inspected(blocks=1, nbytes=4096)
                        inner.add_device_stages({"execute": 1e-6},
                                                fused_q=2)
                        inner.add_device_stages({"d2h": 1e-7},
                                                count=False)
                    inner.add_skip("time_range", 2)
                    for st in ("header_prune", "staging", "prepare",
                               "dispatch", "drain"):
                        inner.add_stage(st, 1e-6)
            if qs is not None:
                qs.finish()
        return time.perf_counter() - t0

    N_PROTO = 5_000
    protocol_loop(500)  # warm
    query_stats.configure(enabled=True)
    record_us = min(protocol_loop(N_PROTO) for _ in range(3)) \
        / N_PROTO * 1e6
    query_stats.configure(enabled=False)
    try:
        noop_us = min(protocol_loop(N_PROTO) for _ in range(3)) \
            / N_PROTO * 1e6
    finally:
        query_stats.configure(enabled=True)
    overhead_pct = (record_us - noop_us) / query_us * 100
    result = {
        "n_entries": n_entries,
        "iters_per_rep": iters,
        "query_us": round(query_us, 1),
        "record_cost_us": round(record_us - noop_us, 2),
        "noop_cost_us": round(noop_us, 3),
        "overhead_pct": round(overhead_pct, 3),
        "ab_overhead_pct": round(ab_overhead_pct, 3),
        "within_2pct": overhead_pct < 2.0,
        "byte_identical": identical,
    }
    assert overhead_pct < 2.0, (
        f"query-stats record cost {record_us - noop_us:.1f}us is "
        f"{overhead_pct:.2f}% of the {query_us:.0f}us query — exceeds "
        "the 2% budget")
    return result


def phase_selftrace_overhead():
    """Dogfood pipeline contract (`selftrace_ingest_enabled`,
    docs/observability.md "Self-hosted tracing"): the gate off is a
    TRUE noop — byte-identical search responses — and the gate ON must
    cost < 2% of an end-to-end request. The request-path additions are
    (a) per-dispatch stage-span lowering, (b) the request span's
    query.* annotation, (c) the breaker/recorder gate reads; export +
    self-ingest ride the flush thread, off the request path. Same shape
    as profile_overhead: the ASSERTED bound is the deterministic
    protocol cost as a fraction of a measured request; the wall-clock
    A/B delta rides along, informational."""
    import json as _json
    import tempfile

    from tempo_tpu.api.http import HTTPApi
    from tempo_tpu.db.tempodb import TempoDBConfig
    from tempo_tpu.modules import App, AppConfig
    from tempo_tpu.observability import selftrace
    from tempo_tpu.observability.selftrace import SELFTRACE
    from tempo_tpu.utils.ids import random_trace_id
    from tempo_tpu.utils.test_data import make_trace

    iters = int(os.environ.get("BENCH_SELFTRACE_ITERS", 40))
    reps = int(os.environ.get("BENCH_SELFTRACE_REPS", 3))
    with tempfile.TemporaryDirectory(prefix="bench-selftrace-") as tmp:
        app = App(AppConfig(
            wal_dir=os.path.join(tmp, "wal"),
            db=TempoDBConfig(auto_mesh=False),
            self_tracing={"enabled": True, "exporter": "self",
                          "selftrace_ingest_enabled": True,
                          "sample_ratio": 1.0,
                          # keep the batch thread quiet mid-timing;
                          # force_flush drains between reps
                          "flush_interval_s": 3600.0}))
        try:
            api = HTTPApi(app)
            for seed in range(1, 5):
                app.push("t1", list(make_trace(random_trace_id(),
                                               seed=seed).batches))
            app.flush_tick(force=True)
            app.poll_tick()
            params = {"tags": "service.name=frontend", "limit": "20"}
            hdr = {"X-Scope-OrgID": "t1"}

            def run_loop(n):
                body = None
                t0 = time.perf_counter()
                for _ in range(n):
                    code, body = api.handle("GET", "/api/search",
                                            params, hdr)
                    assert code == 200
                return time.perf_counter() - t0, body

            run_loop(max(4, iters // 4))  # warm: jit cache + heat
            t_on, t_off = [], []
            b_on = b_off = None
            try:
                for _ in range(reps):
                    selftrace.configure(ingest_enabled=False)
                    dt, b_off = run_loop(iters)
                    t_off.append(dt)
                    selftrace.configure(ingest_enabled=True)
                    dt, b_on = run_loop(iters)
                    t_on.append(dt)
                    app.tracer.processor.force_flush()
            finally:
                selftrace.configure(ingest_enabled=True)
            request_us = min(t_on) / iters * 1e6
            ab_overhead_pct = (min(t_on) - min(t_off)) / min(t_off) * 100
            identical = (_json.dumps(b_on, sort_keys=True)
                         == _json.dumps(b_off, sort_keys=True))
            assert identical, "selftrace gate on/off responses diverged"

            # deterministic protocol cost: exactly what the gate adds
            # to one request — lower a representative 5-stage dispatch
            # record + annotate the request span with the QueryStats
            # headline dict — measured enabled vs disabled (the span
            # itself exists either way under plain self-tracing)
            class _Rec:
                mode = "batched"
                jit = "hit"
                h2d_bytes = 4096
                d2h_bytes = 256
                stages = {"build": 1e-4, "h2d": 2e-4, "compile": 0.0,
                          "execute": 4e-4, "d2h": 1e-4}

            rec = _Rec()
            qd = {"wall_ms": 2.0, "device_seconds": 4e-4,
                  "blocks_inspected": 4,
                  "bytes_inspected": {"host": 1 << 16, "device": 1 << 18},
                  "dispatches": 2, "fused_dispatches": 1}
            tracer = app.tracer

            def protocol_loop(n):
                t0 = time.perf_counter()
                for _ in range(n):
                    with tracer.start_span("bench.request") as span:
                        SELFTRACE.lower_dispatch(rec, parent=span)
                        SELFTRACE.annotate_query(qd)
                return time.perf_counter() - t0

            N_PROTO = 5_000
            protocol_loop(500)  # warm
            on_us = min(protocol_loop(N_PROTO) for _ in range(3)) \
                / N_PROTO * 1e6
            selftrace.configure(ingest_enabled=False)
            try:
                off_us = min(protocol_loop(N_PROTO) for _ in range(3)) \
                    / N_PROTO * 1e6
            finally:
                selftrace.configure(ingest_enabled=True)
            overhead_pct = (on_us - off_us) / request_us * 100
            result = {
                "iters_per_rep": iters,
                "reps": reps,
                "request_us": round(request_us, 1),
                "gate_cost_us": round(on_us - off_us, 2),
                "noop_cost_us": round(off_us, 3),
                "overhead_pct": round(overhead_pct, 3),
                "ab_overhead_pct": round(ab_overhead_pct, 3),
                "within_2pct": overhead_pct < 2.0,
                "byte_identical": identical,
            }
            assert overhead_pct < 2.0, (
                f"selftrace gate cost {on_us - off_us:.1f}us is "
                f"{overhead_pct:.2f}% of the {request_us:.0f}us request "
                "— exceeds the 2% budget")
        finally:
            app.shutdown()
    return result


def phase_freshness():
    """Search-freshness SLO (ROADMAP item 4's acceptance instrument):
    drive a soak-style concurrent write load through the full
    distributor -> ingester -> WAL -> flush -> poll pipeline and
    measure push->searchable end to end with REAL canary round trips.
    Contracts asserted every round:

      - the white-box freshness gauge (tempo_search_freshness_seconds,
        stamped at poll from block end_times) and the black-box canary
        measurement agree within one poll interval;
      - `ingest_telemetry_enabled: false` is a TRUE noop — the WAL
        bytes a push produces are identical on/off;
      - the enabled telemetry record protocol costs < 2% of a push ack.
    """
    import tempfile
    import threading

    from tempo_tpu.modules import App, AppConfig
    from tempo_tpu.observability import ingest_telemetry
    from tempo_tpu.observability import metrics as obs
    from tempo_tpu.observability.ingest_telemetry import (
        TELEMETRY, IngestCanary)
    from tempo_tpu.utils.test_data import make_trace

    soak_s = float(os.environ.get("BENCH_FRESH_SECONDS", 6.0))
    writers = int(os.environ.get("BENCH_FRESH_WRITERS", 2))
    probes = int(os.environ.get("BENCH_FRESH_PROBES", 6))
    flush_every = float(os.environ.get("BENCH_FRESH_FLUSH_S", 0.25))
    poll_every = float(os.environ.get("BENCH_FRESH_POLL_S", 0.5))

    from tempo_tpu.modules import Limits

    tmp = tempfile.mkdtemp(prefix="bench-freshness-")
    # soak limits: the phase measures the pipeline, not tenant pushback
    lim = Limits(ingestion_rate_bytes=1 << 30,
                 ingestion_burst_bytes=1 << 30,
                 max_live_traces=1_000_000)
    app = App(AppConfig(wal_dir=os.path.join(tmp, "wal"),
                        ingest_telemetry_enabled=True, limits=lim))

    def _now_trace(seed: int):
        """A make_trace stamped NOW: the freshness gauge derives from
        block end_times, so soak spans must carry real wall clock."""
        tr = make_trace(os.urandom(16), seed=seed)
        now_ns = time.time_ns()
        for b in tr.batches:
            for ss in b.scope_spans:
                for sp in ss.spans:
                    dur = max(1, (sp.end_time_unix_nano
                                  - sp.start_time_unix_nano)
                              % 1_000_000_000)
                    sp.start_time_unix_nano = now_ns - dur
                    sp.end_time_unix_nano = now_ns
        return tr

    stop = threading.Event()
    pushed = [0] * writers

    def writer(w: int) -> None:
        i = 0
        while not stop.is_set():
            tr = _now_trace(w * 1_000_003 + i)
            try:
                app.push(f"soak-{w}", list(tr.batches))
                pushed[w] += 1
            except Exception:  # noqa: BLE001 — limits under soak are fine
                pass
            i += 1
            # yield: a zero-sleep loop per writer starves the GIL and
            # turns the measurement into a scheduler bench — the load
            # should stress the pipeline, not freeze the poll loop
            time.sleep(0.001)

    def maintenance() -> None:
        last_poll = 0.0
        while not stop.wait(flush_every):
            try:
                app.flush_tick(force=True)
                if time.monotonic() - last_poll >= poll_every:
                    app.poll_tick()
                    last_poll = time.monotonic()
            except Exception:  # noqa: BLE001 — keep the loop alive
                pass

    threads = [threading.Thread(target=writer, args=(w,), daemon=True)
               for w in range(writers)]
    threads.append(threading.Thread(target=maintenance, daemon=True))
    soak_t0 = time.monotonic()
    for t in threads:
        t.start()

    canary = IngestCanary(app.push, app.reader_db.search,
                          tenant="canary", poll_step_s=0.05)
    # warmup probe (not sampled): the FIRST canary search pays the scan
    # kernels' XLA compile, which belongs to the query path, not the
    # write path this phase measures — steady-state probes hit the
    # compile cache like a real deployment's standing canary
    canary.probe_once(timeout_s=60.0)
    canary.probes = canary.failures = 0
    samples: list[float] = []
    gauge_diffs: list[float] = []
    deadline = time.monotonic() + max(soak_s, probes * 2.0) + 30.0
    while len(samples) + canary.failures < probes \
            and time.monotonic() < deadline:
        f = canary.probe_once(timeout_s=15.0)
        if f is None:
            continue
        samples.append(f)
        # the gauge was stamped at the poll that made the canary block
        # visible: it and the measured round trip may differ by at most
        # the time between that poll and the probe's next check — one
        # poll interval (+ the probe's own step)
        gauge = obs.search_freshness.value(tenant="canary")
        if gauge:
            gauge_diffs.append(abs(gauge - f))
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
    # writers run until the probe loop finishes (warmup included), so
    # the rate divides by the ACTUAL elapsed soak wall time — dividing
    # by the nominal soak_s would overstate it by the probe duration
    soak_elapsed = time.monotonic() - soak_t0
    soak_pushed = sum(pushed)

    # ---- ack-overhead contract: telemetry record protocol < 2% ----
    # per-push ack time measured enabled (the shipping default), then
    # the EXACT protocol an enabled push adds (one enabled-check + two
    # perf_counter reads + one histogram observe) timed against the
    # disabled path — deterministic, immune to shared-host noise
    # (profile_overhead's lesson)
    N_ACK = int(os.environ.get("BENCH_FRESH_ACK_ITERS", 300))
    # distinct trace ids per push: re-pushing one id appends to the same
    # live trace until max_bytes_per_trace turns the loop into a limit
    # bench instead of an ack bench
    ack_batches = [list(_now_trace(i).batches) for i in range(64)]

    def ack_loop(n):
        t0 = time.perf_counter()
        for i in range(n):
            app.push("ackbench", ack_batches[i % len(ack_batches)])
        return time.perf_counter() - t0

    ack_loop(30)  # warm
    push_us = min(ack_loop(N_ACK) for _ in range(3)) / N_ACK * 1e6

    def protocol_loop(n):
        t0 = time.perf_counter()
        for _ in range(n):
            if TELEMETRY.enabled:
                t1 = time.perf_counter()
                TELEMETRY.record_push_ack(time.perf_counter() - t1)
        return time.perf_counter() - t0

    N_PROTO = 20_000
    protocol_loop(1000)
    record_us = min(protocol_loop(N_PROTO) for _ in range(3)) \
        / N_PROTO * 1e6
    ingest_telemetry.configure(enabled=False)
    try:
        noop_us = min(protocol_loop(N_PROTO) for _ in range(3)) \
            / N_PROTO * 1e6
    finally:
        ingest_telemetry.configure(enabled=True)
    overhead_pct = (record_us - noop_us) / push_us * 100

    # ---- noop contract: identical WAL bytes with telemetry off ----
    def wal_bytes(enabled: bool) -> bytes:
        ingest_telemetry.configure(enabled=enabled)
        try:
            a = App(AppConfig(
                wal_dir=os.path.join(tmp, f"noop-{enabled}"),
                ingest_telemetry_enabled=enabled))
            for i in range(8):
                tr = make_trace(bytes([i]) * 16, seed=i)
                a.push("noop", list(tr.batches))
            for ing in a.ingesters.values():
                ing.instance("noop").cut_complete_traces(force=True)
            inst = next(iter(a.ingesters.values())).instance("noop")
            with open(inst.head.path, "rb") as f:
                data = f.read()
            with open(inst.head.path + ".search", "rb") as f:
                return data + b"\x00SEARCH\x00" + f.read()
        finally:
            ingest_telemetry.configure(enabled=True)

    byte_identical = wal_bytes(True) == wal_bytes(False)

    # ---- hot-tier gate-on leg (search-live-tail.md): push→searchable
    # through the live tier, NO flush/poll maintenance at all — the
    # rolling stage alone must make a push searchable, under the same
    # soak write load as the baseline leg above. The canary probes the
    # FULL app search path (frontend → ingester leg → hot scan), not
    # the reader TempoDB, which only sees flushed blocks.
    from tempo_tpu.db.tempodb import TempoDBConfig
    from tempo_tpu.search.live_tier import LIVE_TIER

    live_probes = int(os.environ.get("BENCH_FRESH_LIVE_PROBES", probes))
    app2 = App(AppConfig(
        wal_dir=os.path.join(tmp, "wal-live"),
        db=TempoDBConfig(search_live_tier_enabled=True),
        ingest_telemetry_enabled=True, limits=lim))
    stop2 = threading.Event()
    pushed2 = [0] * writers

    def live_writer(w: int) -> None:
        i = 0
        while not stop2.is_set():
            tr = _now_trace(w * 1_000_003 + i)
            try:
                app2.push(f"soak-{w}", list(tr.batches))
                pushed2[w] += 1
            except Exception:  # noqa: BLE001 — limits under soak are fine
                pass
            i += 1
            time.sleep(0.001)

    threads2 = [threading.Thread(target=live_writer, args=(w,),
                                 daemon=True) for w in range(writers)]
    live_t0 = time.monotonic()
    for t in threads2:
        t.start()
    live_canary = IngestCanary(app2.push, app2.search, tenant="canary",
                               poll_step_s=0.01)
    # warmup probe (not sampled): first gate-on search pays the hot
    # kernel's XLA compile — steady state hits the compile cache
    live_canary.probe_once(timeout_s=60.0)
    live_canary.probes = live_canary.failures = 0
    live_samples: list[float] = []
    live_deadline = time.monotonic() + max(soak_s, live_probes * 2.0) + 30.0
    while len(live_samples) + live_canary.failures < live_probes \
            and time.monotonic() < live_deadline:
        f = live_canary.probe_once(timeout_s=15.0)
        if f is not None:
            live_samples.append(f)

    # ---- live_tail sub-phase: standing-query push→notify latency
    # under the same soak load — the subscription is evaluated inside
    # the push micro-batch, so notify lands before the push ack
    from tempo_tpu import tempopb as _pb

    tail_req = _pb.SearchRequest()
    tail_req.tags["service.name"] = "tempo-canary"
    tail_sub = app2.tail_subscribe("canary", tail_req)
    tail_samples: list[float] = []
    tail_missed = 0
    if tail_sub is not None:
        for _ in range(live_probes):
            t0 = time.monotonic()
            app2.push("canary",
                      [live_canary._make_batch("tail-bench")])
            if tail_sub.poll(timeout_s=5.0):
                tail_samples.append(time.monotonic() - t0)
            else:
                tail_missed += 1
        app2.tail_unsubscribe(tail_sub)
    stop2.set()
    for t in threads2:
        t.join(timeout=10.0)
    live_elapsed = time.monotonic() - live_t0
    try:
        app2.shutdown()
    except Exception:  # noqa: BLE001 — bench teardown best-effort
        pass
    # later phases measure the gate-off default; don't leak the tier
    LIVE_TIER.configure(enabled=False)

    def _pct(vals, p):
        if not vals:
            return None
        vals = sorted(vals)
        return round(vals[min(len(vals) - 1, int(p * len(vals)))], 3)

    live_p99 = _pct(live_samples, 0.99)
    tail_p99 = _pct(tail_samples, 0.99)

    samples.sort()

    def pct(p):
        if not samples:
            return None
        return round(samples[min(len(samples) - 1,
                                 int(p * len(samples)))], 3)

    max_diff = round(max(gauge_diffs), 3) if gauge_diffs else None
    # tolerance: one poll interval (the agreement contract) + 1s for the
    # gauge's inherent quantization (BlockMeta.end_time is unix SECONDS,
    # so the gauge floors the push time) + scheduling margin
    tolerance = poll_every + 1.0 + 0.25
    agree = max_diff is not None and max_diff <= tolerance
    result = {
        "soak_s": round(soak_elapsed, 2),
        "writers": writers,
        "traces_pushed": soak_pushed,
        "push_rate_per_s": round(soak_pushed / max(soak_elapsed, 1e-9), 1),
        "flush_interval_s": flush_every,
        "poll_interval_s": poll_every,
        "probes": canary.probes,
        "probe_failures": canary.failures,
        "push_to_searchable_p50_s": pct(0.50),
        "push_to_searchable_p99_s": pct(0.99),
        "gauge_vs_canary_max_diff_s": max_diff,
        "gauge_agrees_within_poll": agree,
        "push_ack_us": round(push_us, 1),
        "record_cost_us": round(record_us - noop_us, 3),
        "overhead_pct": round(overhead_pct, 3),
        "within_2pct": overhead_pct < 2.0,
        "byte_identical": byte_identical,
        # hot-tier gate-on leg: no maintenance loop at all — the rolling
        # stage alone answers, so these numbers ARE the tier's freshness
        "live_tier": {
            "soak_s": round(live_elapsed, 2),
            "traces_pushed": sum(pushed2),
            "probes": live_canary.probes,
            "probe_failures": live_canary.failures,
            "push_to_searchable_p50_s": _pct(live_samples, 0.50),
            "push_to_searchable_p99_s": live_p99,
        },
        "live_tail": {
            "notified": len(tail_samples),
            "missed": tail_missed,
            "push_to_notify_p50_s": _pct(tail_samples, 0.50),
            "push_to_notify_p99_s": tail_p99,
        },
    }
    assert samples, (
        f"no canary probe became searchable ({canary.failures} failures: "
        f"{canary.last_error}) — the flush/poll pipeline is wedged")
    assert agree, (
        f"freshness gauge and canary disagree by {max_diff}s — more than "
        f"one poll interval ({poll_every}s) + the 1s end_time "
        "quantization")
    assert byte_identical, (
        "telemetry on/off produced different WAL bytes — the noop "
        "contract is broken")
    assert overhead_pct < 2.0, (
        f"ingest telemetry record cost {record_us - noop_us:.2f}us is "
        f"{overhead_pct:.2f}% of the {push_us:.0f}us push ack — exceeds "
        "the 2% budget")
    assert live_samples, (
        f"no gate-on canary probe became searchable through the hot "
        f"tier ({live_canary.failures} failures: "
        f"{live_canary.last_error}) — the live tier is wedged")
    # the tentpole SLO: the hot tier answers WITHOUT waiting for
    # flush+poll, so push→searchable collapses from the multi-second
    # maintenance cadence to the push ack + one hot scan
    assert live_p99 is not None and live_p99 < 0.25, (
        f"hot-tier push→searchable p99 {live_p99}s exceeds the 250ms "
        "gate-on budget — the rolling stage is not absorbing pushes "
        "or the scan is falling back")
    assert tail_sub is not None and not tail_missed, (
        f"live tail missed {tail_missed} of {live_probes} standing-"
        "query notifications (sub registered: "
        f"{tail_sub is not None})")
    return result


def phase_chaos():
    """Robustness contract (docs/robustness.md, ISSUE 9 acceptance):

      (a) noop: with the breaker OFF and no faultpoint armed, the
          dispatch guard's protocol cost is < 2% of a dispatch
          (deterministic measurement, the PR 5/7/8 pattern) and
          responses are byte-identical to the breaker-ON healthy run
          (canonicalized: device_seconds is measured wall time).
      (b) chaos soak: a device hang injected MID-SOAK must keep p99
          bounded by the watchdog (no hung thread), sustain throughput
          through the byte-identical host fallback, trip the breaker
          (device_wedged: true sourced from BREAKER STATE, not ad-hoc
          probing), and recover through half-open after the fault
          clears.
    """
    import json as _json
    import tempfile

    from tempo_tpu import robustness, tempopb
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.backend.types import (
        BlockMeta, NAME_SEARCH, NAME_SEARCH_HEADER,
    )
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.encoding.v2.compression import compress
    from tempo_tpu.observability import metrics as obs
    from tempo_tpu.observability.profile import device_status

    n_blocks = int(os.environ.get("BENCH_CHAOS_BLOCKS", 16))
    entries_per_block = int(os.environ.get("BENCH_CHAOS_ENTRIES", 16_384))
    rounds = int(os.environ.get("BENCH_CHAOS_ROUNDS", 15))
    watchdog_s = float(os.environ.get("BENCH_CHAOS_WATCHDOG_S", 0.5))
    total = n_blocks * entries_per_block

    def canon(resp):
        r = tempopb.SearchResponse()
        r.CopyFrom(resp)
        # measured wall time / placement split move by design —
        # identity is about the ANSWER (traces + deterministic metrics)
        r.metrics.device_seconds = 0.0
        r.metrics.inspected_bytes_device = 0
        return r.SerializeToString()

    with tempfile.TemporaryDirectory() as td:
        be = LocalBackend(td + "/blocks")
        db = TempoDB(be, td + "/wal", TempoDBConfig(
            search_breaker_enabled=True,
            search_breaker_fault_threshold=3,
            search_breaker_cooldown_s=0.5,
            search_device_dispatch_timeout_s=watchdog_s))
        metas = []
        for s in range(n_blocks):
            pages = build_corpus(entries_per_block, seed=s)
            m = BlockMeta(tenant_id="bench", encoding="none")
            blob = compress(pages.to_bytes(), "none")
            hdr = dict(pages.header)
            hdr["encoding"] = "none"
            hdr["compressed_size"] = len(blob)
            be.write("bench", m.block_id, NAME_SEARCH, blob)
            be.write("bench", m.block_id, NAME_SEARCH_HEADER,
                     _json.dumps(hdr).encode())
            metas.append(m)
        db.blocklist.update("bench", add=metas)

        req = tempopb.SearchRequest()
        req.tags["service.name"] = "svc-007"
        req.tags["http.status_code"] = "500"
        req.limit = 20
        robustness.BREAKER.reset()
        r = db.search("bench", req)
        assert r.metrics.inspected_traces == total
        base = canon(db.search("bench", req).response())

        def run_rounds(n):
            lats = []
            for _ in range(n):
                t0 = time.perf_counter()
                got = canon(db.search("bench", req).response())
                lats.append(time.perf_counter() - t0)
                assert got == base, "response diverged from baseline"
            lats.sort()
            return lats

        # ---- healthy baseline (breaker ON, closed) ----
        healthy = run_rounds(rounds)
        healthy_p50 = healthy[len(healthy) // 2]
        healthy_p99 = healthy[-1]

        # ---- (a) noop contract: breaker OFF ----
        robustness.BREAKER.enabled = False
        assert not robustness.GUARD.active
        off = canon(db.search("bench", req).response())
        noop_identical = off == base
        assert noop_identical, "breaker-off response diverged"
        # deterministic guard protocol cost: the inactive guard is two
        # attribute reads + a lambda call — time it against the bare
        # call and take it as a fraction of a measured dispatch
        N_PROTO = 50_000

        def fn():
            return None

        def loop_guarded(n):
            g = robustness.GUARD
            t0 = time.perf_counter()
            for _ in range(n):
                g.run("bench_probe", fn)
            return time.perf_counter() - t0

        def loop_bare(n):
            t0 = time.perf_counter()
            for _ in range(n):
                fn()
            return time.perf_counter() - t0

        loop_guarded(1000), loop_bare(1000)  # warm
        guard_us = min(loop_guarded(N_PROTO) for _ in range(3)) \
            / N_PROTO * 1e6
        bare_us = min(loop_bare(N_PROTO) for _ in range(3)) \
            / N_PROTO * 1e6
        dispatch_us = healthy_p50 * 1e6
        overhead_pct = (guard_us - bare_us) / dispatch_us * 100
        assert overhead_pct < 2.0, (
            f"guard protocol cost {guard_us - bare_us:.2f}us is "
            f"{overhead_pct:.3f}% of the {dispatch_us:.0f}us query — "
            "exceeds the 2% noop budget")
        robustness.BREAKER.enabled = True

        # ---- (b) chaos soak: wedge mid-soak ----
        robustness.BREAKER.reset()
        fallback0 = obs.scan_dispatches.value(mode="host_fallback")
        robustness.FAULTS.arm("device_dispatch_hang",
                              delay_s=watchdog_s * 20, count=10_000)
        t_wedge0 = time.perf_counter()
        wedged = run_rounds(rounds)
        wedge_wall = time.perf_counter() - t_wedge0
        dstat = device_status()
        device_wedged = bool(dstat.get("wedged"))
        breaker_during = dstat.get("breaker", {})
        robustness.FAULTS.disarm_all()
        wedged_p99 = wedged[-1]
        fallback_n = (obs.scan_dispatches.value(mode="host_fallback")
                      - fallback0)
        # bounded: worst round pays at most the watchdog (+ host scan);
        # after the breaker trips rounds are pure host-fallback speed
        bound = watchdog_s * 3 + max(1.0, 10 * healthy_p99)
        assert wedged_p99 < bound, (
            f"wedged p99 {wedged_p99:.2f}s exceeds bound {bound:.2f}s — "
            "the hang leaked into the serving path")
        assert device_wedged, (
            "breaker never tripped during injection (device_wedged "
            "should read true from breaker state)")
        assert fallback_n >= 1, "no host-fallback dispatch recorded"

        # ---- recovery after un-wedge ----
        deadline = time.time() + 30
        recovered = False
        while time.time() < deadline:
            got = canon(db.search("bench", req).response())
            assert got == base
            if robustness.BREAKER.state == "closed":
                recovered = True
                break
            time.sleep(0.1)
        snap = robustness.BREAKER.snapshot()
        assert recovered, f"breaker never recovered: {snap}"
        assert snap["transitions"].get("open->half_open", 0) >= 1
        assert snap["transitions"].get("half_open->closed", 0) >= 1
        robustness.BREAKER.reset()

        return {
            "blocks": n_blocks,
            "rounds": rounds,
            "watchdog_s": watchdog_s,
            "healthy_p50_ms": round(healthy_p50 * 1e3, 2),
            "healthy_p99_ms": round(healthy_p99 * 1e3, 2),
            "wedged_p50_ms": round(wedged[len(wedged) // 2] * 1e3, 2),
            "wedged_p99_ms": round(wedged_p99 * 1e3, 2),
            "wedged_p99_bound_ms": round(bound * 1e3, 1),
            "fallback_traces_per_sec": round(
                total * rounds / wedge_wall),
            "host_fallback_dispatches": int(fallback_n),
            "device_wedged": device_wedged,
            "breaker_during_injection": breaker_during,
            "breaker_transitions": snap["transitions"],
            "noop_identical": noop_identical,
            "guard_cost_us": round(guard_us - bare_us, 3),
            "noop_overhead_pct": round(overhead_pct, 4),
            "within_2pct": overhead_pct < 2.0,
            "recovered": recovered,
        }


def phase_ownership():
    """Owner-routed HBM contract (docs/search-hbm-ownership.md,
    ISSUE 11 acceptance): simulated two-owner serving over ONE shared
    hot blocklist whose staged footprint exceeds a single host's HBM
    budget.

      - independent caches (ownership OFF): both hosts serve the full
        stream over the full blocklist under the same budget — the LRU
        thrashes the shared hot set and every round re-stages;
      - owner-routed (ON): each host stages only its owned placement
        groups (which fit the budget) and serves the rest through the
        byte-identical host route — strictly fewer re-stage bytes and a
        higher HBM hit ratio, with responses byte-identical to OFF.
    """
    import json as _json
    import tempfile

    from tempo_tpu import tempopb
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.backend.types import (
        BlockMeta, NAME_SEARCH, NAME_SEARCH_HEADER,
    )
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.encoding.v2.compression import compress
    from tempo_tpu.observability import metrics as obs
    from tempo_tpu.search import ownership

    n_blocks = int(os.environ.get("BENCH_OWNERSHIP_BLOCKS", 24))
    entries_per_block = int(os.environ.get("BENCH_OWNERSHIP_ENTRIES", 8192))
    rounds = int(os.environ.get("BENCH_OWNERSHIP_ROUNDS", 6))
    budget_frac = float(os.environ.get("BENCH_OWNERSHIP_BUDGET_FRAC", 0.55))

    def canon(resp):
        r = tempopb.SearchResponse()
        r.CopyFrom(resp)
        r.metrics.device_seconds = 0.0
        r.metrics.inspected_bytes_device = 0
        return r.SerializeToString()

    with tempfile.TemporaryDirectory() as td:
        be = LocalBackend(td + "/blocks")
        metas = []
        for s in range(n_blocks):
            pages = build_corpus(entries_per_block, E=256, seed=s)
            # unique trace ids: the identity assert compares MERGED
            # results, and build_corpus's all-zero ids would collapse
            # every entry into one trace whose merge winner depends on
            # group completion order, not on routing
            rng = np.random.default_rng(10_000 + s)
            pages.trace_ids = rng.integers(
                0, 255, size=pages.trace_ids.shape, dtype=np.uint8)
            m = BlockMeta(tenant_id="bench", encoding="none")
            blob = compress(pages.to_bytes(), "none")
            hdr = dict(pages.header)
            hdr["encoding"] = "none"
            hdr["compressed_size"] = len(blob)
            be.write("bench", m.block_id, NAME_SEARCH, blob)
            be.write("bench", m.block_id, NAME_SEARCH_HEADER,
                     _json.dumps(hdr).encode())
            metas.append(m)

        req = tempopb.SearchRequest()
        req.tags["service.name"] = "svc-007"
        req.limit = 10_000  # never early-quits: every group is served

        def mkdb(tag, budget):
            # small groups (few blocks each) so ownership has real
            # granularity to split; coalescing off — serial stream
            db = TempoDB(be, f"{td}/wal-{tag}", TempoDBConfig(
                auto_mesh=False,
                search_max_batch_pages=64,
                search_batch_cache_bytes=budget,
                search_coalesce_max_queries=0))
            db.blocklist.update("bench", add=metas)
            return db

        # sizing pass: the full blocklist's staged footprint
        sizer = mkdb("size", 64 << 30)
        sizer.search("bench", req)
        hot_set_bytes = sizer.batcher._cache_total
        budget = max(1, int(hot_set_bytes * budget_frac))

        def serve(tag, enable):
            """Two fresh hosts serve `rounds` passes of the stream; in
            ownership mode each request is answered AS its host (the
            process-wide self_id flips — serial, so race-free)."""
            dbs = [mkdb(f"{tag}-h0", budget), mkdb(f"{tag}-h1", budget)]
            if enable:
                ownership.configure(enabled=True, members="h0,h1",
                                    self_id="h0", groups=32)
            else:
                ownership.OWNERSHIP.reset()
            h2d0 = obs.h2d_bytes.value()
            hit0 = obs.batch_cache_events.value(result="hit")
            miss0 = obs.batch_cache_events.value(result="miss")
            outs = []
            t0 = time.perf_counter()
            for _ in range(rounds):
                for i, db in enumerate(dbs):
                    if enable:
                        ownership.OWNERSHIP.self_id = f"h{i}"
                    outs.append(canon(db.search("bench", req).response()))
            wall = time.perf_counter() - t0
            hits = obs.batch_cache_events.value(result="hit") - hit0
            misses = obs.batch_cache_events.value(result="miss") - miss0
            stats = {
                "restage_bytes": int(obs.h2d_bytes.value() - h2d0),
                "hbm_hits": int(hits),
                "hbm_misses": int(misses),
                "hbm_hit_ratio": round(hits / max(1, hits + misses), 4),
                "wall_s": round(wall, 3),
            }
            ownership.OWNERSHIP.reset()
            return outs, stats

        off_outs, off = serve("off", enable=False)
        on_outs, on = serve("on", enable=True)
        identical = on_outs == off_outs
        assert identical, "ownership on/off responses diverged"
        assert on["restage_bytes"] < off["restage_bytes"], (
            f"owner routing re-staged {on['restage_bytes']} bytes, "
            f"independent caches {off['restage_bytes']} — the placement "
            "split saved nothing")
        assert on["hbm_hit_ratio"] >= off["hbm_hit_ratio"]

        # ---- hot-skew leg (ISSUE 18): heat-adaptive replication +
        # hedged dispatch vs plain rf=1 under an injected slow primary.
        # A zipf-ish stream sends ~80% of dispatches at ONE hot group
        # and ~20% at an alternate group with the same owner; the
        # primary's budget is 0.55x that two-group working set, so the
        # alternate traffic keeps thrashing the hot group out of HBM
        # and every hot re-stage pays the armed `h2d_delay`. With rf=2
        # the hot group heat-promotes, every hot dispatch hedges to the
        # replica host (full budget, hot-resident) after a fixed 25 ms
        # delay, and the hot-group p99 collapses from ~h2d_delay to
        # ~hedge delay — while every response stays byte-identical and
        # the replica stages ONLY promoted groups (duplicate-stage
        # bytes strictly bounded, residency accounting conserved).
        from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
        from tempo_tpu.modules.querier import Querier
        from tempo_tpu.modules.ring import Ring
        from tempo_tpu.robustness import FAULTS

        n_samples = int(os.environ.get("BENCH_HEDGE_SAMPLES", 150))
        slow_s = float(os.environ.get("BENCH_HEDGE_H2D_DELAY_S", 0.12))
        hedge_ms = 25.0
        block_bytes = hot_set_bytes / n_blocks
        skew_budget = max(1, int(2 * block_bytes * budget_frac))

        def p99(xs):
            return sorted(xs)[min(len(xs) - 1, int(0.99 * len(xs)))]

        class _HostQuerier:
            """Serve AS one fleet member: identity is context-scoped
            (ownership.self_as), so concurrent hedged attempts on their
            daemon threads each see their own host, race-free."""

            def __init__(self, db, member):
                self.db = db
                self.member = member
                self.inner = Querier(db, Ring(), {})

            def search_blocks(self, breq):
                with ownership.self_as(self.member):
                    return self.inner.search_blocks(breq)

        def mk_breq(template):
            breq = tempopb.SearchBlocksRequest()
            breq.CopyFrom(template)
            breq.search_req.CopyFrom(req)
            breq.tenant_id = "bench"
            return breq

        def hedge_leg(tag, rf):
            db0 = mkdb(f"skew-{tag}-h0", skew_budget)  # primary: thrashes
            db1 = mkdb(f"skew-{tag}-h1", 64 << 30)     # replica: resident
            fe = QueryFrontend(
                [_HostQuerier(db0, "h0"), _HostQuerier(db1, "h1")],
                FrontendConfig(retries=3, target_bytes_per_job=1 << 30,
                               batch_jobs_per_request=1))
            # configure AFTER mkdb: TempoDB.__init__ applies its own
            # (disabled) ownership config
            ownership.configure(
                enabled=True, members="h0,h1", self_id="h0", groups=32,
                rf=rf, hot_rate=0.5, hedge_delay_ms=hedge_ms)
            by_block = {}
            for payload, template, owner, width in fe._search_batches("bench"):
                by_block[payload[0][0].block_id] = (
                    payload, template, owner, width)
            h0_blocks = [m.block_id for m in metas
                         if ownership.OWNERSHIP.owner_of(m.block_id) == "h0"]
            hot = h0_blocks[0]
            alt = next(b for b in h0_blocks[1:]
                       if (ownership.OWNERSHIP.group_of(b)
                           != ownership.OWNERSHIP.group_of(hot)))

            def dispatch(block_id):
                payload, template, owner, width = by_block[block_id]
                breq = mk_breq(template)
                t0 = time.perf_counter()
                r = fe._dispatch_batch(breq, owner, width, block_id)
                return time.perf_counter() - t0, canon(r)

            up0 = obs.hbm_replica_promotions.value(dir="up")
            hw0 = obs.hedged_dispatches.value(result="hedge_won")
            if rf > 1:
                # promote the hot group up front (the serving loop's
                # record_access gets there too — this pins the promoted
                # state for the whole measured stream) and pre-stage
                # the replica un-faulted so the first hedge never races
                # a cold staging put
                for _ in range(60):
                    ownership.OWNERSHIP.record_access(hot)
                assert ownership.OWNERSHIP.replica_indices(hot), \
                    "hot group failed to heat-promote"
                fe.queriers[1].search_blocks(mk_breq(by_block[hot][1]))
            # warm-up un-faulted: primary residency + kernel compile
            dispatch(hot)
            dispatch(alt)
            dispatch(hot)

            walls_hot, outs = [], []
            with FAULTS.armed("h2d_delay", delay_s=slow_s, count=10**6):
                for i in range(n_samples):
                    blk = alt if i % 5 == 4 else hot
                    w, out = dispatch(blk)
                    outs.append(out)
                    if blk is hot:
                        walls_hot.append(w)
            # residency accounting conserved on BOTH hosts: no negative
            # bytes, cache total == sum of its entries
            for db in (db0, db1):
                ent = sum(e.nbytes for e in db.batcher._cache.values())
                assert db.batcher._cache_total == ent >= 0, (
                    f"{tag}: cache accounting drifted "
                    f"({db.batcher._cache_total} != {ent})")
            stats = {
                "rf": rf,
                "hot_dispatches": len(walls_hot),
                "p50_s": round(sorted(walls_hot)[len(walls_hot) // 2], 4),
                "p99_s": round(p99(walls_hot), 4),
                "replica_staged_bytes": int(db1.batcher._cache_total),
                "promotions_up": int(
                    obs.hbm_replica_promotions.value(dir="up") - up0),
                "hedge_won": int(
                    obs.hedged_dispatches.value(result="hedge_won") - hw0),
            }
            ownership.OWNERSHIP.reset()
            return outs, stats

        rf1_outs, rf1 = hedge_leg("rf1", rf=1)
        rf2_outs, rf2 = hedge_leg("rf2", rf=2)
        assert rf1_outs == rf2_outs, (
            "hedged rf=2 responses diverged from rf=1")
        assert rf2["p99_s"] < rf1["p99_s"], (
            f"hedged rf=2 hot-group p99 {rf2['p99_s']}s did not beat "
            f"rf=1 {rf1['p99_s']}s under a {slow_s}s slow primary")
        # rf=1 never touches the second host; rf=2 replicates ONLY the
        # promoted group(s) — hot plus at most the alternate if its
        # in-stream rate crossed the threshold — never the whole
        # blocklist (24 blocks) the primary carries
        assert rf1["replica_staged_bytes"] == 0, (
            "rf=1 leg staged bytes on the non-owner host")
        assert rf2["replica_staged_bytes"] <= 2.5 * block_bytes, (
            f"replica staged {rf2['replica_staged_bytes']} bytes — more "
            f"than the promoted groups (block ~{int(block_bytes)} bytes)")
        assert rf2["hedge_won"] >= 1, "no hedge ever won against the slow primary"
        assert rf2["promotions_up"] >= 1 and rf1["promotions_up"] == 0
        hot_skew = {
            "samples": n_samples,
            "h2d_delay_s": slow_s,
            "hedge_delay_ms": hedge_ms,
            "skew_budget_bytes": int(skew_budget),
            "byte_identical": rf1_outs == rf2_outs,
            "rf1": rf1,
            "rf2": rf2,
            "p99_speedup": round(rf1["p99_s"] / max(rf2["p99_s"], 1e-9), 2),
        }

        return {
            "blocks": n_blocks,
            "rounds": rounds,
            "hosts": 2,
            "hot_set_bytes": int(hot_set_bytes),
            "hbm_budget_bytes": int(budget),
            "byte_identical": identical,
            "ownership_off": off,
            "ownership_on": on,
            "restage_bytes_saved": off["restage_bytes"] - on["restage_bytes"],
            "owner_routed": int(obs.hbm_owner_routed.value(route="owner")),
            "non_owner_host_routed": int(
                obs.hbm_owner_routed.value(route="non_owner_host")),
            "hot_skew": hot_skew,
        }


def phase_packing():
    """Packed HBM residency contract (docs/search-packed-residency.md,
    ISSUE 13 acceptance): over a mixed-cardinality tag-heavy corpus,

      - `search_packed_residency: true` stages STRICTLY fewer physical
        HBM bytes than false (target >= 40% fewer on this corpus);
      - responses are byte-identical packed on vs off;
      - at a FIXED HBM budget sized below the unpacked hot set, the
        packed layout keeps more batches resident and serves a higher
        HBM hit ratio — the bytes saved become residency;
      - scan throughput is recorded for both (asserted no worse than a
        conservative noise floor on shared-CPU hosts; the exact ratio
        ships in detail.packing).

    Runs on whatever backend jax resolves; a CPU fallback is labeled by
    the standard `_breaker`/`device_wedged` rider, never silent.
    """
    import json as _json
    import tempfile

    from tempo_tpu import tempopb
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.backend.types import (
        BlockMeta, NAME_SEARCH, NAME_SEARCH_HEADER,
    )
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.encoding.v2.compression import compress
    from tempo_tpu.observability import metrics as obs
    from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
    from tempo_tpu.search.data import SearchData

    n_blocks = int(os.environ.get("BENCH_PACKING_BLOCKS", 18))
    entries_per_block = int(os.environ.get("BENCH_PACKING_ENTRIES", 4096))
    rounds = int(os.environ.get("BENCH_PACKING_ROUNDS", 4))
    budget_frac = float(os.environ.get("BENCH_PACKING_BUDGET_FRAC", 0.55))

    def mk_block(s):
        """Tag-heavy entries (kv is ~70% of a batch's bytes) cycling
        the width classes the planner picks per block union: tiny
        dictionaries (≤15 values → 4-bit codes vs the legacy int8),
        ~240-value dictionaries (uint8 codes vs int16 — the ISSUE's
        '200 distinct values' case), and the same with durations past
        the uint16 boundary so the quantized+residual path runs for
        real. Per-NAMESPACE cardinality: the width is chosen from the
        block's value-dictionary UNION across its 12 tag namespaces."""
        rng = np.random.default_rng(1000 + s)
        card = [1, 20, 20][s % 3]      # union: 12 / ~240 / ~240 values
        dur_max = [40_000, 60_000, 1 << 20][s % 3]
        entries = []
        for i in range(entries_per_block):
            sd = SearchData(
                trace_id=rng.bytes(16),
                start_s=int(rng.integers(1, 5_000)),
                end_s=int(rng.integers(5_000, 10_000)),
                dur_ms=int(rng.integers(0, dur_max)),
            )
            sd.kvs = {"service.name":
                      {f"svc-{int(rng.integers(0, card)):05d}"}}
            for t in range(11):
                sd.kvs[f"tag{t:02d}"] = {
                    f"t{t}-{int(rng.integers(0, card)):05d}"}
            entries.append(sd)
        return ColumnarPages.build(entries, PageGeometry(256, 16))

    def canon(resp):
        r = tempopb.SearchResponse()
        r.CopyFrom(resp)
        r.metrics.device_seconds = 0.0
        r.metrics.inspected_bytes_device = 0
        return r.SerializeToString()

    with tempfile.TemporaryDirectory() as td:
        be = LocalBackend(td + "/blocks")
        metas = []
        for s in range(n_blocks):
            pages = mk_block(s)
            m = BlockMeta(tenant_id="bench", encoding="none")
            blob = compress(pages.to_bytes(), "none")
            hdr = dict(pages.header)
            hdr["encoding"] = "none"
            hdr["compressed_size"] = len(blob)
            be.write("bench", m.block_id, NAME_SEARCH, blob)
            be.write("bench", m.block_id, NAME_SEARCH_HEADER,
                     _json.dumps(hdr).encode())
            metas.append(m)

        # limits sized above any possible match count: under a tight
        # budget the two layouts cache (and therefore order) groups
        # differently, and an early-quit freezes whichever subset
        # happened to finish first — the documented residency-order
        # tradeoff, not a packing property
        reqs = []
        for i in range(6):
            r = tempopb.SearchRequest()
            r.tags[f"tag{i:02d}"] = f"t{i}-000"
            r.limit = 200_000
            reqs.append(r)
        edge = 1 << 5  # q-bucket edge at the 2^20 duration class
        r = tempopb.SearchRequest()
        r.min_duration_ms = 3 * edge
        r.max_duration_ms = 1 << 18
        r.limit = 200_000
        reqs.append(r)

        def mkdb(tag, enabled, budget):
            # one 16-page block per staged group: widths are a
            # per-batch property (the max over member blocks), so
            # homogeneous groups let every cardinality class keep its
            # own narrowest width — the production analog is tenants
            # whose dictionary shape is uniform within a group
            db = TempoDB(be, f"{td}/wal-{tag}", TempoDBConfig(
                auto_mesh=False, host_state_dir="",
                search_max_batch_pages=16,
                search_batch_cache_bytes=budget,
                search_coalesce_max_queries=0,
                search_packed_residency=enabled))
            db.blocklist.update("bench", add=metas)
            return db

        def serve(tag, enabled, budget):
            db = mkdb(tag, enabled, budget)
            hit0 = obs.batch_cache_events.value(result="hit")
            miss0 = obs.batch_cache_events.value(result="miss")
            h2d0 = obs.h2d_bytes.value()
            outs = []
            traces = 0
            t0 = time.perf_counter()
            for _ in range(rounds):
                for req in reqs:
                    res = db.search("bench", req)
                    traces += int(res.metrics.inspected_traces)
                    outs.append(canon(res.response()))
            wall = time.perf_counter() - t0
            hits = obs.batch_cache_events.value(result="hit") - hit0
            misses = obs.batch_cache_events.value(result="miss") - miss0
            stats = {
                "physical_bytes": int(db.batcher._cache_total),
                "logical_bytes": int(db.batcher._cache_logical),
                "resident_batches": len(db.batcher._cache),
                "restage_bytes": int(obs.h2d_bytes.value() - h2d0),
                "hbm_hits": int(hits),
                "hbm_misses": int(misses),
                "hbm_hit_ratio": round(hits / max(1, hits + misses), 4),
                "wall_s": round(wall, 3),
                "traces_per_s": round(traces / max(wall, 1e-9)),
            }
            return outs, stats

        # unbudgeted pass: the pure physical-bytes + byte-identity claim
        off_outs, off = serve("off", False, 64 << 30)
        on_outs, on = serve("on", True, 64 << 30)
        assert on_outs == off_outs, "packed on/off responses diverged"
        assert on["physical_bytes"] < off["physical_bytes"], (
            "packing saved no staged bytes")
        saved = 1 - on["physical_bytes"] / max(1, off["physical_bytes"])
        # acceptance target is >= 40% on this corpus; assert a hard
        # floor with margin for geometry padding drift
        assert saved >= 0.35, f"only {saved:.1%} physical bytes saved"
        # the logical (unpacked-equivalent) view is layout-independent
        # (budget totals additionally carry per-predicate query-table
        # bytes, which the logical split leaves out)
        assert on["logical_bytes"] == off["logical_bytes"]
        # throughput: no worse, within the shared-CPU noise floor
        # (exact ratio recorded either way)
        tput_ratio = on["traces_per_s"] / max(1, off["traces_per_s"])
        assert tput_ratio >= 0.7, (
            f"packed scan throughput regressed to {tput_ratio:.2f}x")

        # fixed-budget pass: bytes saved become residency — budget sized
        # below the unpacked hot set, so unpacked thrashes where packed
        # stays resident
        budget = max(1, int(off["physical_bytes"] * budget_frac))
        boff_outs, boff = serve("boff", False, budget)
        bon_outs, bon = serve("bon", True, budget)
        assert bon_outs == boff_outs
        assert bon["resident_batches"] >= boff["resident_batches"]
        assert bon["hbm_hit_ratio"] >= boff["hbm_hit_ratio"]

        return {
            "blocks": n_blocks,
            "entries_per_block": entries_per_block,
            "rounds": rounds,
            "physical_bytes_saved_ratio": round(saved, 4),
            "throughput_ratio_on_vs_off": round(tput_ratio, 3),
            "byte_identical": True,
            "packing_off": off,
            "packing_on": on,
            "fixed_budget_bytes": int(budget),
            "fixed_budget_off": boff,
            "fixed_budget_on": bon,
        }


def phase_structural():
    """Structural query engine contract (ISSUE 14,
    docs/search-structural-queries.md): a parent/child + descendant +
    aggregate query mix over a span-bearing corpus, asserting

      - byte-identity: the compiled device path's match set equals the
        host reference evaluator's (structural.eval_host), per query;
      - a throughput floor vs the equivalent POST-FILTER baseline (the
        pre-structural architecture: run the legacy scan, fetch, then
        evaluate the structural predicate per trace on host) — the
        compiled path must not lose to interpreting the tree per row;
      - the compiled plan tree with per-node device-seconds lands in
        this phase's detail (the ?explain=1 surface).
    """
    import tempfile

    from tempo_tpu import tempopb
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.search import ir, structural
    from tempo_tpu.search.columnar import PageGeometry
    from tempo_tpu.search.data import (SearchData, SpanData,
                                       encode_search_data)

    n_blocks = int(os.environ.get("BENCH_STRUCTURAL_BLOCKS", 6))
    entries_per_block = int(os.environ.get("BENCH_STRUCTURAL_ENTRIES",
                                           4096))
    rounds = int(os.environ.get("BENCH_STRUCTURAL_ROUNDS", 3))
    svcs = [f"svc-{i:02d}" for i in range(12)]

    def mk_entries(s):
        rng = np.random.default_rng(2000 + s)
        out = []
        for i in range(entries_per_block):
            sd = SearchData(
                trace_id=rng.bytes(16),
                start_s=int(rng.integers(1, 5_000)),
                end_s=int(rng.integers(5_000, 10_000)),
                dur_ms=int(rng.integers(1, 30_000)),
            )
            svc = svcs[int(rng.integers(0, len(svcs)))]
            sd.kvs = {"service.name": {svc},
                      "env": {"prod" if i % 2 else "dev"}}
            n_sp = int(rng.integers(1, 8))
            for j in range(n_sp):
                sd.spans.append(SpanData(
                    parent=(-1 if j == 0 else int(rng.integers(0, j))),
                    dur_ms=int(rng.integers(1, 2_000)),
                    kind=int(rng.integers(0, 6)),
                    kvs={"service.name":
                         {svcs[int(rng.integers(0, len(svcs)))]},
                         "name": {f"op{int(rng.integers(0, 4))}"}}))
            out.append(sd)
        return out

    queries = {
        "parent_child": ir.parse(
            '{"child": {"parent": {"tag": {"k": "service.name",'
            ' "v": "svc-03"}}, "child": {"dur": {"min_ms": 500}}}}'),
        "descendant": ir.parse(
            '{"desc": {"anc": {"kind": "server"},'
            ' "span": {"tag": {"k": "name", "v": "op1"}}}}'),
        "count": ir.parse(
            '{"count": {"of": {"dur": {"min_ms": 1000}},'
            ' "op": ">", "n": 2}}'),
        "quantile": ir.parse(
            '{"quantile": {"of": {"tag": {"k": "name", "v": "op"}},'
            ' "q": "0.9", "op": ">=", "ms": 1200}}'),
    }

    with tempfile.TemporaryDirectory() as td:
        be = LocalBackend(td + "/blocks")
        db = TempoDB(be, td + "/wal", TempoDBConfig(
            auto_mesh=False, search_structural_enabled=True,
            search_geometry=PageGeometry(256, 8)))
        corpus = []
        for s in range(n_blocks):
            entries = sorted(mk_entries(s), key=lambda sd: sd.trace_id)
            corpus.extend(entries)
            db.write_block_direct(
                "bench",
                [(sd.trace_id, encode_search_data(sd), sd.start_s,
                  sd.end_s) for sd in entries],
                search_entries=entries)

        total = len(corpus)
        results = {}
        compiled_wall = 0.0
        for name, expr in queries.items():
            want = {sd.trace_id for sd in corpus
                    if structural.eval_host(expr, sd)}
            req = tempopb.SearchRequest()
            req.limit = total
            structural.attach_query(req, expr)
            # warm (stage + compile), then measure
            db.search("bench", req)
            t0 = time.perf_counter()
            for _ in range(rounds):
                res = db.search("bench", req)
            wall = (time.perf_counter() - t0) / rounds
            compiled_wall += wall
            got = {bytes.fromhex(m.trace_id)
                   for m in res.response().traces}
            assert got == want, (
                f"{name}: compiled match set diverged from the host "
                f"reference ({len(got)} vs {len(want)})")
            # post-filter-on-host baseline: the legacy scan already ran
            # once above; the honest extra cost of the old architecture
            # is interpreting the structural tree per fetched trace
            t0 = time.perf_counter()
            n_match = sum(1 for sd in corpus
                          if structural.eval_host(expr, sd))
            base_wall = time.perf_counter() - t0
            results[name] = {
                "matches": len(want),
                "compiled_ms": round(wall * 1e3, 3),
                "post_filter_baseline_ms": round(base_wall * 1e3, 3),
                "speedup_vs_post_filter": round(base_wall / max(wall,
                                                                1e-9), 2),
            }
            _ = n_match

        # throughput floor: the compiled mix must beat interpreting the
        # tree per row (generous floor for shared-CPU noise)
        base_total = sum(r["post_filter_baseline_ms"]
                         for r in results.values()) / 1e3
        assert compiled_wall <= base_total / 0.5, (
            f"compiled structural mix ({compiled_wall:.3f}s) lost to the "
            f"post-filter baseline ({base_total:.3f}s) by >2x")

        # explain surface: per-node device-seconds in the plan tree
        req = tempopb.SearchRequest()
        req.limit = 10
        req.explain = True
        structural.attach_query(req, queries["parent_child"])
        stats = json.loads(
            db.search("bench", req).response().metrics.query_stats_json)
        nodes = stats["structural"]["nodes"]
        assert nodes and all("device_ms" in n for n in nodes)

        concurrency = _structural_concurrency_subphase(td, mk_entries)
        mixed = _structural_mixed_subphase(td, mk_entries)
        sharded_leg = _structural_sharded_span_leg(mk_entries)
        remainder_leg = _structural_remainder_leg(mk_entries)

        return {
            "blocks": n_blocks,
            "entries_per_block": entries_per_block,
            "total_traces": total,
            "byte_identical": True,
            "compiled_mix_traces_per_s": round(
                total * len(queries) / max(compiled_wall, 1e-9)),
            "post_filter_traces_per_s": round(
                total * len(queries) / max(base_total, 1e-9)),
            "queries": results,
            "explain_plan_nodes": nodes,
            "structural_concurrency": concurrency,
            "structural_mixed": mixed,
            "mesh_sharded_spans": sharded_leg,
            "mesh_remainder_pages": remainder_leg,
        }


def _structural_concurrency_subphase(td, mk_entries):
    """`structural_concurrency` sub-phase (ISSUE 15): a barrier-synced
    8-way SAME-PLAN-SHAPE structural load against the serving path with
    plan-shape stacking on. Asserts the fused dispatches per request
    land well below 1 (>= 2x fewer kernel launches than the solo-flush
    behavior) and that every concurrent response is byte-identical to
    the same query run serially."""
    import threading

    from tempo_tpu import tempopb
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.search import ir, structural
    from tempo_tpu.search.columnar import PageGeometry
    from tempo_tpu.search.data import encode_search_data

    be = LocalBackend(td + "/blocks-conc")
    db = TempoDB(be, td + "/wal-conc", TempoDBConfig(
        auto_mesh=False, search_structural_enabled=True,
        search_structural_stack_enabled=True,
        search_coalesce_window_s=0.05,
        search_geometry=PageGeometry(256, 8)))
    corpus = []
    for s in range(2):
        entries = sorted(mk_entries(s), key=lambda sd: sd.trace_id)
        corpus.extend(entries)
        db.write_block_direct(
            "bench",
            [(sd.trace_id, encode_search_data(sd), sd.start_s, sd.end_s)
             for sd in entries],
            search_entries=entries)
    N = 8
    exprs = [ir.parse(
        '{"child": {"parent": {"tag": {"k": "service.name",'
        ' "v": "svc-%02d"}}, "child": {"dur": {"min_ms": %d}}}}'
        % (i % 12, 100 * (i + 1))) for i in range(N)]

    def search_one(expr):
        req = tempopb.SearchRequest()
        req.limit = len(corpus)
        structural.attach_query(req, expr)
        resp = db.search("bench", req).response()
        return sorted(m.trace_id for m in resp.traces), \
            int(resp.metrics.inspected_traces)

    serial = [search_one(e) for e in exprs]   # also warms stage+compile
    co = db.batcher.coalescer
    d0, q0 = co.dispatches, co.queries
    out = [None] * N
    barrier = threading.Barrier(N)

    def one(i):
        barrier.wait()
        out[i] = search_one(exprs[i])

    t0 = time.perf_counter()
    threads = [threading.Thread(target=one, args=(i,)) for i in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    for i in range(N):
        assert out[i] == serial[i], f"query {i} diverged under stacking"
    dispatches = co.dispatches - d0
    served = co.queries - q0
    assert served == N
    per_request = dispatches / N
    # the acceptance floor: >= 2x fewer launches than solo (which costs
    # one dispatch per request)
    assert per_request <= 0.5, (
        f"stacking fused too little: {dispatches} dispatches for {N} "
        "same-plan requests")
    return {
        "requests": N,
        "dispatches": dispatches,
        "dispatches_per_request": round(per_request, 3),
        "stacked_queries": co.structural_stacked,
        "stack_ratio": co.stats()["structural_stack_ratio"],
        "byte_identical_vs_serial": True,
        "wall_ms": round(wall * 1e3, 3),
    }


def _structural_mixed_subphase(td, mk_entries):
    """`structural_mixed` sub-phase (ISSUE 16): a barrier-synced 8-way
    MIXED-plan structural load (>= 3 distinct plan shapes that
    canonicalize into one bucket) against the serving path with
    shape-bucketed stacking on. Asserts the bucketed dispatches per
    request land at or below 0.5 (>= 2x fewer launches than the
    per-plan flush the exact-plan grouping costs), byte-identity vs the
    same queries run serially, and cost-apportionment conservation —
    the members' attributed device seconds sum to the fused dispatch
    records' totals."""
    import threading

    from tempo_tpu import tempopb
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.observability.profile import PROFILER
    from tempo_tpu.search import ir, structural
    from tempo_tpu.search.columnar import PageGeometry
    from tempo_tpu.search.data import encode_search_data

    be = LocalBackend(td + "/blocks-mixed")
    db = TempoDB(be, td + "/wal-mixed", TempoDBConfig(
        auto_mesh=False, search_structural_enabled=True,
        search_structural_stack_enabled=True,
        search_structural_bucket_enabled=True,
        search_coalesce_window_s=0.05,
        search_geometry=PageGeometry(256, 8)))
    corpus = []
    for s in range(2):
        entries = sorted(mk_entries(s), key=lambda sd: sd.trace_id)
        corpus.extend(entries)
        db.write_block_direct(
            "bench",
            [(sd.trace_id, encode_search_data(sd), sd.start_s, sd.end_s)
             for sd in entries],
            search_entries=entries)
    # three DISTINCT plan shapes, one canonical bucket (3 span slots +
    # exists+root -> NS 4 / NT 2 / relational): the mixed dashboard
    # traffic exact-plan grouping cannot fuse
    shapes = [
        lambda i: (
            '{"child": {"parent": {"tag": {"k": "service.name",'
            ' "v": "svc-%02d"}}, "child": {"dur": {"min_ms": %d}}}}'
            % (i % 12, 100 * (i + 1))),
        lambda i: (
            '{"child": {"parent": {"tag": {"k": "service.name",'
            ' "v": "svc-%02d"}}, "child": {"kind": "server"}}}'
            % (i % 12)),
        lambda i: (
            '{"child": {"parent": {"dur": {"min_ms": %d}},'
            ' "child": {"tag": {"k": "name", "v": "op1"}}}}'
            % (100 * (i + 1))),
    ]
    N = 8
    exprs = [ir.parse(shapes[i % 3](i)) for i in range(N)]
    n_plans = len({str(e) for e in exprs})
    assert n_plans >= 3

    def search_one(expr):
        req = tempopb.SearchRequest()
        req.limit = len(corpus)
        structural.attach_query(req, expr)
        resp = db.search("bench", req).response()
        return sorted(m.trace_id for m in resp.traces), \
            int(resp.metrics.inspected_traces)

    serial = [search_one(e) for e in exprs]   # also warms stage+compile
    co = db.batcher.coalescer
    d0, q0, b0 = co.dispatches, co.queries, co.structural_bucketed
    out = [None] * N
    barrier = threading.Barrier(N)

    def one(i):
        barrier.wait()
        out[i] = search_one(exprs[i])

    t0 = time.perf_counter()
    threads = [threading.Thread(target=one, args=(i,))
               for i in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    for i in range(N):
        assert out[i] == serial[i], \
            f"query {i} diverged under bucketed stacking"
    dispatches = co.dispatches - d0
    served = co.queries - q0
    assert served == N
    per_request = dispatches / N
    # the acceptance floor: >= 2x fewer launches than the per-plan
    # flush (which costs one dispatch per request here — every window
    # holds mixed plans)
    assert per_request <= 0.5, (
        f"bucketing fused too little: {dispatches} dispatches for {N} "
        f"mixed-plan requests across {n_plans} shapes")
    assert co.structural_bucketed - b0 > 0, "no bucketed fusion booked"
    conserved = _mixed_conservation_leg(mk_entries, exprs)
    stats = co.stats()
    return {
        "requests": N,
        "plan_shapes": n_plans,
        "dispatches": dispatches,
        "dispatches_per_request": round(per_request, 3),
        "bucketed_queries": co.structural_bucketed - b0,
        "bucket_occupancy": {
            bk: row["occupancy"]
            for bk, row in stats.get("buckets", {}).items()},
        "byte_identical_vs_serial": True,
        "cost_conserved": conserved,
        "wall_ms": round(wall * 1e3, 3),
    }


def _mixed_conservation_leg(mk_entries, exprs):
    """Cost-apportionment conservation for a bucketed MIXED-plan fused
    dispatch: exactly one size-flushed group through the coalescer, and
    per dispatch stage the members' attributed shares sum to the fused
    record's totals to the float bit (query_stats.apportion weights by
    each member's ACTIVE node tables — pad slots are never billed)."""
    import threading

    from tempo_tpu import tempopb
    from tempo_tpu.observability.profile import PROFILER
    from tempo_tpu.search import query_stats, structural
    from tempo_tpu.search.batcher import QueryCoalescer
    from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
    from tempo_tpu.search.engine import resolve_top_k
    from tempo_tpu.search.multiblock import MultiBlockEngine, compile_multi
    from tempo_tpu.search.structural import compile_structural

    N = len(exprs)
    blocks = [ColumnarPages.build(
        sorted(mk_entries(9), key=lambda sd: sd.trace_id),
        PageGeometry(256, 8))]
    eng = MultiBlockEngine(top_k=256)
    batch = eng.stage(blocks)
    co = QueryCoalescer(eng, window_s=60.0, max_queries=N,
                        active_fn=lambda: N)
    mqs = []
    for e in exprs:
        req = tempopb.SearchRequest()
        req.limit = 256
        structural.attach_query(req, e)
        mq = compile_multi(blocks, req, cache_on=batch)
        mq.structural = compile_structural(
            e, blocks, cache_on=batch, staged_dicts=batch.staged_dicts)
        mqs.append(mq)
    stats = [query_stats.QueryStats("bench") for _ in range(N)]
    futs = [None] * N
    caught: list[dict] = []
    listener = caught.append

    def submit(i):
        with query_stats.activate(stats[i]):
            futs[i] = co.submit(batch, mqs[i],
                                resolve_top_k(eng.top_k, mqs[i].limit),
                                peers=N)

    PROFILER.add_listener(listener)
    try:
        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(N)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for f in futs:
            f.result(timeout=120)
    finally:
        PROFILER._listeners.remove(listener)
    assert co.queries == N and co.dispatches == 1, (
        f"mixed group did not size-flush as ONE bucketed dispatch "
        f"({co.dispatches} dispatches)")
    fused = [rd for rd in caught if rd.get("mode") == "coalesced"]
    assert len(fused) == 1
    totals = {k: v / 1e3 for k, v in fused[0]["stages_ms"].items()}
    for stage, total in totals.items():
        attributed = sum(qs.device_stages.get(stage, 0.0)
                         for qs in stats)
        assert abs(attributed - total) <= 1e-12 * max(1.0, total), (
            f"stage {stage!r}: apportioned {attributed!r}s does not "
            f"conserve the dispatch total {total!r}s")
    return True


def _structural_remainder_leg(mk_entries):
    """Mesh remainder-shard leg of the `structural` phase (ISSUE 16):
    stage a NON-multiple page count over the mesh with the pow2 vs the
    minimal-multiple (remainder-shard) layout, report the staged-byte
    reduction, and assert byte-identical answers through the dist
    kernels both ways."""
    import jax

    from tempo_tpu import tempopb
    from tempo_tpu.search import ir, structural
    from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
    from tempo_tpu.search.multiblock import MultiBlockEngine, compile_multi
    from tempo_tpu.search.structural import STRUCTURAL, compile_structural

    if len(jax.devices()) < 2:
        return {"skipped": "single device — no mesh to shard over"}
    from tempo_tpu.parallel import make_mesh

    mesh = make_mesh()
    n_sh = int(mesh.devices.size)
    geo = PageGeometry(256, 8)
    blocks = [ColumnarPages.build(
        sorted(mk_entries(s), key=lambda sd: sd.trace_id), geo)
        for s in range(2)]
    # append one-page blocks until the page total is ragged enough that
    # the minimal shard multiple actually beats the pow2 layout (the
    # measured-saving contract must hold at any corpus-size override)
    pool: list = []
    pool_seed = 2

    def minimal_vs_pow2(total):
        m = max(n_sh, -(-total // n_sh) * n_sh)
        p = max(n_sh, 1)
        while p < total:
            p *= 2
        return m, p

    while True:
        total_pages = sum(b.n_pages for b in blocks)
        m, p = minimal_vs_pow2(total_pages)
        if m < p:
            break
        while len(pool) < geo.entries_per_page:
            pool.extend(sorted(mk_entries(pool_seed),
                               key=lambda sd: sd.trace_id))
            pool_seed += 1
        blocks.append(ColumnarPages.build(
            pool[:geo.entries_per_page], geo))
        del pool[:geo.entries_per_page]
    expr = ir.parse(
        '{"child": {"parent": {"tag": {"k": "service.name",'
        ' "v": "svc-03"}}, "child": {"dur": {"min_ms": 500}}}}')

    def run(remainder: bool):
        prev = STRUCTURAL.remainder_pages
        STRUCTURAL.remainder_pages = remainder
        try:
            eng = MultiBlockEngine(top_k=4096, mesh=mesh)
            batch = eng.stage(blocks)
            req = tempopb.SearchRequest()
            req.limit = 4096
            structural.attach_query(req, expr)
            mq = compile_multi(blocks, req, cache_on=batch)
            mq.structural = compile_structural(
                expr, blocks, cache_on=batch,
                staged_dicts=batch.staged_dicts)
            count, _ins, scores, idx = eng.scan(batch, mq)
            got = frozenset(
                (int(s), int(i))
                for s, i in zip(scores.tolist(), idx.tolist()) if s >= 0)
            pages = int(batch.device["kv_key"].shape[0])
            return count, got, pages, int(batch.device_nbytes)
        finally:
            STRUCTURAL.remainder_pages = prev

    p_count, p_got, p_pages, p_bytes = run(False)
    r_count, r_got, r_pages, r_bytes = run(True)
    assert (p_count, p_got) == (r_count, r_got), \
        "remainder-shard layout diverged from the pow2 layout"
    assert r_pages < p_pages, (
        f"remainder layout saved nothing: {r_pages} vs {p_pages} staged "
        f"pages for {total_pages} real pages on {n_sh} shards")
    return {
        "shards": n_sh,
        "real_pages": total_pages,
        "pow2_staged_pages": p_pages,
        "remainder_staged_pages": r_pages,
        "pow2_staged_bytes": p_bytes,
        "remainder_staged_bytes": r_bytes,
        "staged_byte_ratio": round(r_bytes / max(1, p_bytes), 3),
        "byte_identical": True,
        "matches": int(p_count),
    }


def _structural_sharded_span_leg(mk_entries):
    """Mesh-sharded-span leg of the `structural` phase (ISSUE 15):
    stage one span-bearing batch over the mesh with the replicated vs
    the segment-aligned sharded layout, report per-shard span bytes
    (sharded ~ 1/P of replicated), and assert byte-identical answers
    through the dist kernel both ways."""
    import jax

    from tempo_tpu import tempopb
    from tempo_tpu.search import ir, structural
    from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
    from tempo_tpu.search.multiblock import MultiBlockEngine, compile_multi
    from tempo_tpu.search.structural import STRUCTURAL, compile_structural

    if len(jax.devices()) < 2:
        return {"skipped": "single device — no mesh to shard over"}
    from tempo_tpu.parallel import make_mesh

    mesh = make_mesh()
    n_sh = int(mesh.devices.size)
    geo = PageGeometry(256, 8)
    blocks = [ColumnarPages.build(
        sorted(mk_entries(s), key=lambda sd: sd.trace_id), geo)
        for s in range(2)]
    expr = ir.parse(
        '{"child": {"parent": {"tag": {"k": "service.name",'
        ' "v": "svc-03"}}, "child": {"dur": {"min_ms": 500}}}}')

    def run(shard: bool):
        prev = STRUCTURAL.shard_spans
        STRUCTURAL.shard_spans = shard
        try:
            eng = MultiBlockEngine(top_k=4096, mesh=mesh)
            batch = eng.stage(blocks)
            req = tempopb.SearchRequest()
            req.limit = 4096
            structural.attach_query(req, expr)
            mq = compile_multi(blocks, req, cache_on=batch)
            mq.structural = compile_structural(
                expr, blocks, cache_on=batch,
                staged_dicts=batch.staged_dicts)
            count, _ins, scores, idx = eng.scan(batch, mq)
            got = frozenset(
                (int(s), int(i))
                for s, i in zip(scores.tolist(), idx.tolist()) if s >= 0)
            span_total = sum(int(a.nbytes)
                             for a in batch.span_device.values())
            # replicated layout pins the FULL segment on every shard;
            # the sharded layout splits its global arrays 1/P each
            per_shard = (span_total // n_sh) if batch.span_sharded \
                else span_total
            assert batch.span_sharded == shard
            return count, got, per_shard
        finally:
            STRUCTURAL.shard_spans = prev

    rep_count, rep_got, rep_bytes = run(False)
    sh_count, sh_got, sh_bytes = run(True)
    assert (rep_count, rep_got) == (sh_count, sh_got), \
        "sharded span layout diverged from replicated"
    return {
        "shards": n_sh,
        "replicated_span_bytes_per_shard": rep_bytes,
        "sharded_span_bytes_per_shard": sh_bytes,
        "span_hbm_ratio": round(sh_bytes / max(1, rep_bytes), 3),
        "byte_identical": True,
        "matches": int(rep_count),
    }


def phase_analytics():
    """Device-side aggregate analytics contract (ISSUE 19,
    docs/search-analytics.md):

      - ingest: a paired native-summary corpus (client + server rows of
        each edge in the same push, unique span ids) through the batched
        device reduction vs the per-span Python walk — the registries
        must come out BYTE-identical (exposition, LRU order, pairing
        store) and the batched path >= 5x the walk's rows/s (hard floor
        below the target for shared-CPU noise; exact ratio recorded);
      - query: ?agg=red answers over the serving path must equal a
        plain-python reference aggregator exactly, and the aggregate's
        marginal cost vs the same queries without ?agg= is recorded.

    Runs with the gate flipped per leg; the standard `_breaker` /
    `device_wedged` riders label any mid-run trip.
    """
    import bisect as _bisect
    import struct as _struct
    import tempfile

    from tempo_tpu import tempopb
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.modules.generator import (MetricsGenerator,
                                             ServiceGraphProcessor,
                                             SpanMetricsProcessor)
    from tempo_tpu.search.analytics import ANALYTICS, MS_BUCKETS, attach_agg
    from tempo_tpu.search.data import SearchData, encode_search_data

    n_rows = int(os.environ.get("BENCH_ANALYTICS_ROWS", 8192))
    n_pushes = int(os.environ.get("BENCH_ANALYTICS_PUSHES", 10))
    floor = float(os.environ.get("BENCH_ANALYTICS_FLOOR", 4.0))
    q_entries = int(os.environ.get("BENCH_ANALYTICS_ENTRIES", 4096))
    q_rounds = int(os.environ.get("BENCH_ANALYTICS_ROUNDS", 3))

    # ---- ingest leg -------------------------------------------------
    _ROW = _struct.Struct("<6IQQ8s8s")
    svcs = [f"svc-{i:02d}" for i in range(8)]
    ops = [f"op-{i}" for i in range(4)]
    strs = svcs + ops

    def mk_push(seed):
        """client+server rows of each edge in ONE push, globally unique
        span ids — every pair completes in-batch, the walk's hot path."""
        rng = np.random.default_rng(3000 + seed)
        tids = [rng.bytes(16) for _ in range(256)]
        rows = []
        sid = seed * n_rows + 1
        for _ in range(n_rows // 2):
            ti = int(rng.integers(0, len(tids)))
            name = len(svcs) + int(rng.integers(0, len(ops)))
            start = int(rng.integers(0, 1 << 40))
            dur = int(rng.integers(1, 20_000_000_000))
            csid = sid.to_bytes(8, "little")
            ssid = (sid + 1).to_bytes(8, "little")
            sid += 2
            rows.append((ti, int(rng.integers(0, len(svcs))), name, 3,
                         2 * int(rng.integers(0, 2)), 0, start,
                         start + dur, csid, b"\x00" * 8))
            rows.append((ti, int(rng.integers(0, len(svcs))),
                         len(svcs) + int(rng.integers(0, len(ops))), 2,
                         2 * int(rng.integers(0, 2)), 0, start,
                         start + dur // 2, ssid, csid))
        out = [_struct.pack("<I", len(strs))]
        for s in strs:
            b = s.encode()
            out.append(_struct.pack("<H", len(b)))
            out.append(b)
        out.append(_struct.pack("<I", len(rows)))
        for r in rows:
            out.append(_ROW.pack(*r))
        return b"".join(out), tids

    pushes = [mk_push(s) for s in range(n_pushes)]

    def feed(enabled):
        ANALYTICS.configure(enabled=enabled, min_rows=1)
        if enabled:  # compile warm-up outside the measurement
            scratch = MetricsGenerator()
            scratch.push_summary_blob("warm", *pushes[0])
        gen = MetricsGenerator()
        t0 = time.perf_counter()
        for blob, tids in pushes:
            gen.push_summary_blob("bench", blob, tids)
        wall = time.perf_counter() - t0
        _reg, procs = gen._instance("bench")
        spm = next(p for p in procs
                   if isinstance(p, SpanMetricsProcessor))
        sgp = next(p for p in procs
                   if isinstance(p, ServiceGraphProcessor))
        snap = (gen.collect("bench"), list(spm._series),
                {k: v[:3] for k, v in sgp._store.items()})
        return wall, snap

    walk_wall, walk_snap = feed(False)
    dev_wall, dev_snap = feed(True)
    ANALYTICS.configure(enabled=False)
    assert dev_snap == walk_snap, (
        "batched ingest registries diverged from the per-span walk")
    speedup = walk_wall / max(dev_wall, 1e-9)
    total_rows = n_rows * n_pushes
    assert speedup >= floor, (
        f"batched ingest only {speedup:.2f}x the walk "
        f"(target 5x, floor {floor}x)")

    ingest = {
        "rows_per_push": n_rows,
        "pushes": n_pushes,
        "walk_rows_per_s": round(total_rows / max(walk_wall, 1e-9)),
        "device_rows_per_s": round(total_rows / max(dev_wall, 1e-9)),
        "speedup": round(speedup, 2),
        "byte_identical": True,
    }

    # ---- query leg --------------------------------------------------
    def mk_entries(s):
        rng = np.random.default_rng(4000 + s)
        out = []
        for i in range(q_entries):
            sd = SearchData(
                trace_id=rng.bytes(16),
                start_s=int(rng.integers(1, 5_000)),
                end_s=int(rng.integers(5_000, 10_000)),
                dur_ms=int(rng.integers(1, 30_000)),
            )
            sd.root_service = svcs[int(rng.integers(0, len(svcs)))]
            sd.kvs = {"service.name": {sd.root_service},
                      "env": {"prod" if i % 2 else "dev"}}
            if rng.random() < 0.25:
                sd.kvs["error"] = {"true"}
            out.append(sd)
        return out

    def ref_series(corpus, pred):
        series = {}
        for sd in corpus:
            if not pred(sd):
                continue
            s = series.setdefault(sd.root_service, {
                "calls": 0, "errors": 0,
                "hist": [0] * (len(MS_BUCKETS) + 1)})
            s["calls"] += 1
            s["errors"] += int("true" in sd.kvs.get("error", ()))
            s["hist"][_bisect.bisect_left(MS_BUCKETS, sd.dur_ms)] += 1
        return series

    preds = {
        "env=prod": lambda sd: "prod" in next(iter(sd.kvs["env"])),
        "env=dev": lambda sd: "dev" in next(iter(sd.kvs["env"])),
        "svc-03": lambda sd: "svc-03" == sd.root_service,
    }
    tag_of = {"env=prod": ("env", "prod"), "env=dev": ("env", "dev"),
              "svc-03": ("service.name", "svc-03")}

    with tempfile.TemporaryDirectory() as td:
        be = LocalBackend(td + "/blocks")
        db = TempoDB(be, td + "/wal", TempoDBConfig(
            auto_mesh=False, search_analytics_enabled=True))
        corpus = []
        for s in range(3):
            entries = sorted(mk_entries(s), key=lambda sd: sd.trace_id)
            corpus.extend(entries)
            db.write_block_direct(
                "bench",
                [(sd.trace_id, encode_search_data(sd), sd.start_s,
                  sd.end_s) for sd in entries],
                search_entries=entries)

        def run(name, agg):
            k, v = tag_of[name]
            req = tempopb.SearchRequest()
            req.limit = len(corpus)
            req.tags[k] = v
            if agg:
                attach_agg(req, "red")
            db.search("bench", req)        # warm
            t0 = time.perf_counter()
            for _ in range(q_rounds):
                resp = db.search("bench", req).response()
            return (time.perf_counter() - t0) / q_rounds, resp

        queries = {}
        agg_wall = plain_wall = 0.0
        for name, pred in preds.items():
            w_plain, _ = run(name, agg=False)
            w_agg, resp = run(name, agg=True)
            agg_wall += w_agg
            plain_wall += w_plain
            got = json.loads(resp.metrics.agg_json)
            want = ref_series(corpus, pred)
            assert got["series"] == want, (
                f"?agg=red diverged from the host reference on {name}")
            queries[name] = {
                "matches": sum(s["calls"] for s in want.values()),
                "plain_ms": round(w_plain * 1e3, 3),
                "agg_ms": round(w_agg * 1e3, 3),
            }

        query = {
            "entries": len(corpus),
            "rounds": q_rounds,
            "reference_identical": True,
            "agg_overhead_ratio": round(
                agg_wall / max(plain_wall, 1e-9), 3),
            "queries": queries,
        }

    return {"ingest": ingest, "query": query}


def phase_scale_10k():
    n_blocks = int(os.environ.get("BENCH_SCALE_BLOCKS", 10_000))
    if not n_blocks:
        return None
    return bench_scale(n_blocks,
                       int(os.environ.get("BENCH_SCALE_ENTRIES", 512)),
                       int(os.environ.get("BENCH_SCALE_ITERS", 7)))


def phase_scale_large_blocks():
    n_blocks = int(os.environ.get("BENCH_LARGE_BLOCKS", 600))
    if not n_blocks:
        return None
    return bench_scale_large(
        n_blocks,
        int(os.environ.get("BENCH_LARGE_ENTRIES", 65_536)),
        int(os.environ.get("BENCH_LARGE_ITERS", 3)))


PHASES = {
    "probe": phase_probe,
    "single": phase_single,
    "multiblock": phase_multiblock,
    "serving": phase_serving,
    "coalesced_serving": phase_coalesced_serving,
    "high_cardinality": phase_high_cardinality,
    "high_cardinality_full": phase_high_cardinality_full,
    "profile_overhead": phase_profile_overhead,
    "query_stats_overhead": phase_query_stats_overhead,
    "selftrace_overhead": phase_selftrace_overhead,
    "freshness": phase_freshness,
    "chaos": phase_chaos,
    "ownership": phase_ownership,
    "packing": phase_packing,
    "structural": phase_structural,
    "analytics": phase_analytics,
    "scale_10k": phase_scale_10k,
    "scale_large_blocks": phase_scale_large_blocks,
}

# Per-phase subprocess deadlines (seconds); env-overridable via
# BENCH_TIMEOUT_<NAME>. Sized ~3x the r4 self-run wall times so a healthy
# run never trips them, while a wedge loses only the phase it hit.
PHASE_TIMEOUTS = {
    "probe": 60.0,
    "single": 420.0,
    "multiblock": 300.0,
    "serving": 420.0,
    "coalesced_serving": 420.0,
    "high_cardinality": 300.0,
    "high_cardinality_full": 420.0,
    "profile_overhead": 300.0,
    "query_stats_overhead": 300.0,
    "selftrace_overhead": 300.0,
    "freshness": 560.0,  # baseline leg + hot-tier gate-on leg + tail
    "chaos": 420.0,
    "ownership": 540.0,
    "packing": 420.0,
    "structural": 600.0,
    "analytics": 420.0,
    "scale_10k": 900.0,
    "scale_large_blocks": 1200.0,
}


# env keys that change a phase's MEASUREMENT (platform + corpus sizes);
# harness plumbing (ckpt paths, deadlines, test hooks) is excluded. Used
# to fingerprint checkpoints so BENCH_RESUME never mixes results across
# platforms or corpus configs.
_FP_EXCLUDE = ("BENCH_CKPT", "BENCH_RESUME", "BENCH_WATCHDOG",
               "BENCH_TIMEOUT", "BENCH_PHASES", "BENCH_TEST",
               "BENCH_CPU_FALLBACK")


def _fingerprint(env: dict) -> dict:
    knobs = {k: v for k, v in sorted(env.items())
             if k.startswith("BENCH_") and not k.startswith(_FP_EXCLUDE)}
    return {"jax_platforms": env.get("JAX_PLATFORMS", ""), "knobs": knobs}


def _phase_main(name: str) -> int:
    """Child entry: run one phase, print its result as the last stdout
    line, and checkpoint it to $BENCH_CKPT_FILE (atomic rename) so the
    number survives even if the parent dies before reading the pipe."""
    hang = os.environ.get("BENCH_TEST_HANG_PHASE")
    if hang == name:  # test hook: simulate a wedged accelerator tunnel
        # BENCH_TEST_HANG_TIMES=N hangs only the first N attempts (counted
        # across child processes via a sidecar file) so tests can model a
        # tunnel that wedges TPU probes but answers the CPU fallback
        times = int(os.environ.get("BENCH_TEST_HANG_TIMES", 0))
        cnt_path = (os.environ.get("BENCH_CKPT_FILE") or name) + ".hangcount"
        try:
            with open(cnt_path) as f:
                n = int(f.read().strip() or 0) + 1
        except (OSError, ValueError):
            n = 1
        with open(cnt_path, "w") as f:
            f.write(str(n))
        if times <= 0 or n <= times:
            while True:
                time.sleep(3600)

    from tempo_tpu.utils.jaxenv import honor_jax_platforms

    honor_jax_platforms(required=True)  # bench WILL use jax: fail loudly
    result = PHASES[name]()
    if isinstance(result, dict) and "_profile" not in result:
        # per-phase dispatch-stage breakdown (observability/profile.py):
        # each phase child is its own process, so the process profiler's
        # aggregates ARE this phase's stage profile — the trajectory
        # files stop being opaque wall-clock totals
        try:
            from tempo_tpu.observability.profile import PROFILER

            snap = PROFILER.snapshot(recent=0)
            if snap["aggregates"]:
                result["_profile"] = {
                    "aggregates": snap["aggregates"],
                    "jit_cache": snap["jit_cache"],
                    "bytes": snap["bytes"],
                }
        except Exception:  # noqa: BLE001 — telemetry must not fail a phase
            pass
    if isinstance(result, dict) and "_breaker" not in result:
        # the device circuit breaker's verdict rides every phase result:
        # a phase whose dispatches tripped the breaker mid-run is a
        # wedge the HEADLINE must see (sourced from breaker state, not
        # ad-hoc probing — the r04/r05 lesson). The chaos phase resets
        # its deliberate trips before returning, so this only fires on
        # a REAL wedge.
        try:
            from tempo_tpu.robustness import BREAKER

            snap = BREAKER.snapshot()
            if snap["transitions"] or snap["faults_in_window"]:
                result["_breaker"] = snap
        except Exception:  # noqa: BLE001 — telemetry must not fail a phase
            pass
    doc = json.dumps(result)
    ckpt = os.environ.get("BENCH_CKPT_FILE")
    if ckpt:
        tmp = ckpt + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"_fp": _fingerprint(dict(os.environ)),
                       "data": result}, f)
        os.replace(tmp, ckpt)
    print(doc, flush=True)
    return 0


# ---------------------------------------------------------------------------
# Orchestrator — stdlib only; NEVER imports jax (a wedged tunnel hangs the
# first device op in C code, uninterruptibly — only a subprocess kill works).
# ---------------------------------------------------------------------------

_current_child: subprocess.Popen | None = None


def _kill_child(p: subprocess.Popen) -> None:
    try:
        os.killpg(p.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        p.kill()


def _run_child(name: str, timeout_s: float, ckpt_dir: str,
               extra_env: dict | None = None,
               timeout_reason: str = "device tunnel likely wedged"):
    """Run one phase subprocess; on wedge/timeout SIGKILL its whole
    process group and fall back to its checkpoint file if one landed.
    Only a checkpoint written by THIS child counts — a stale file from
    a previous (resumed) run must not make a wedged device look healthy."""
    global _current_child
    path = os.path.join(ckpt_dir, f"{name}.json")
    env = dict(os.environ)
    if extra_env:
        env.update(extra_env)
    env["BENCH_CKPT_FILE"] = path
    t_child_start = time.time()
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--phase", name],
        stdout=subprocess.PIPE, stderr=None, text=True,
        start_new_session=True, env=env, cwd=_HERE)
    _current_child = p

    def fresh_ckpt():
        try:
            if os.path.getmtime(path) >= t_child_start - 1.0:
                with open(path) as f:
                    obj = json.load(f)
                if isinstance(obj, dict) and "_fp" in obj:
                    return obj["data"]
                return obj
        except OSError:
            pass
        return None

    try:
        out, _ = p.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        _kill_child(p)
        p.wait()
        return fresh_ckpt() or {
            "error": f"phase '{name}' timed out after {timeout_s:.0f}s "
                     f"— {timeout_reason}; phase killed"}
    finally:
        _current_child = None
    if p.returncode != 0:
        return fresh_ckpt() or {
            "error": f"phase '{name}' exited rc={p.returncode}"}
    for line in reversed((out or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{") or line == "null":
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return fresh_ckpt() or {
        "error": f"phase '{name}' produced no parseable result"}


def _failed(r) -> bool:
    return isinstance(r, dict) and "error" in r


def _assemble(results: dict) -> dict:
    """Build the single final JSON doc from whatever phases finished —
    same shape as every prior round so BENCH_r0N files stay comparable;
    wedged phases carry {"error": ...} instead of numbers."""
    def _strip(r):
        """Phase result without its `_profile`/`_breaker` riders (those
        land once, under detail, not duplicated per config)."""
        if isinstance(r, dict) and ("_profile" in r or "_breaker" in r):
            return {k: v for k, v in r.items()
                    if k not in ("_profile", "_breaker")}
        return r

    # per-phase dispatch-stage profiles, collected before the strip
    prof_stages = {k: v["_profile"] for k, v in results.items()
                   if isinstance(v, dict) and "_profile" in v}
    # phases whose device circuit breaker was NOT closed at exit — a
    # mid-phase wedge the headline must surface, sourced from breaker
    # state rather than ad-hoc probing (the chaos phase's deliberate
    # trips reset before return, so anything here is real)
    breaker_wedged = {
        k: v["_breaker"] for k, v in results.items()
        if isinstance(v, dict)
        and v.get("_breaker", {}).get("state") not in (None, "closed")}
    results = {k: _strip(v) if k != "degraded" else v
               for k, v in results.items()}
    single = results.get("single")
    probe = results.get("probe") or {}
    ok = isinstance(single, dict) and not _failed(single)
    tpu_rate = single["tpu_traces_per_sec"] if ok else 0
    cpu_rate = single["cpu_traces_per_sec"] if ok else 0
    serving = results.get("serving")
    if isinstance(serving, dict) and not _failed(serving) \
            and "relay_sync_ms" in probe:
        serving = dict(serving)
        serving["relay_sync_floor_ms"] = probe["relay_sync_ms"]
    doc = {
        "metric": "columnar_tag_scan_throughput",
        "value": tpu_rate,
        "unit": "traces/s",
        "vs_baseline": round(tpu_rate / cpu_rate, 3) if ok and cpu_rate else 0,
        "detail": {
            "platform": probe.get("platform", "unknown"),
            "device": probe.get("device", "unknown"),
            "n_entries": (single or {}).get("n_entries"),
            "matches": (single or {}).get("matches"),
            "cpu_traces_per_sec": cpu_rate,
            "query": "service.name=svc-007 AND http.status_code=500 AND dur>=500ms",
            "configs": {
                "duration_only_traces_per_sec":
                    (single or {}).get("duration_only_traces_per_sec")
                    if ok else None,
                "multiblock": results.get("multiblock"),
                "serving_path": serving,
                "coalesced_serving": results.get("coalesced_serving"),
                "high_cardinality": results.get("high_cardinality"),
                "high_cardinality_full": results.get("high_cardinality_full"),
                "scale_10k": results.get("scale_10k"),
                "scale_large_blocks": results.get("scale_large_blocks"),
            },
        },
    }
    # the dictionary-probe trajectory (host prefilter vs device probe)
    # surfaces at the TOP level of detail so round-over-round consumers
    # track the optimization without digging through per-phase configs
    probe_ms = {}
    for ph in ("high_cardinality", "high_cardinality_full"):
        r = results.get(ph)
        if isinstance(r, dict) and not _failed(r):
            probe_ms[ph] = {
                "distinct_values": r.get("distinct_values"),
                "dict_prefilter_ms": r.get("dict_prefilter_ms"),
                "device_probe_ms": r.get("device_probe_ms"),
                "device_probe_stage_ms": r.get("device_probe_stage_ms"),
            }
    if probe_ms:
        doc["detail"]["dict_probe"] = probe_ms
    # offload-planner calibration table (predicted vs measured stage
    # times, decisions taken, mispredict rate) — the high-cardinality
    # phases run planner-on with identical-match asserts and ship the
    # verdicts here, spanning the measured crossover (1M and 10M values)
    planner_tbl = {}
    for ph in ("high_cardinality", "high_cardinality_full"):
        r = results.get(ph)
        if isinstance(r, dict) and not _failed(r) and r.get("planner"):
            planner_tbl[ph] = dict(r["planner"],
                                   distinct_values=r.get("distinct_values"))
    if planner_tbl:
        doc["detail"]["planner"] = planner_tbl
    # dispatch-profiler telemetry: the overhead contract measurement plus
    # every phase's per-(mode, stage) aggregates — the trajectory now
    # carries WHERE device time went, not just wall-clock totals
    prof: dict = {}
    ov = results.get("profile_overhead")
    if isinstance(ov, dict) and not _failed(ov):
        prof["overhead"] = ov
    elif isinstance(ov, dict):
        prof["overhead"] = {"error": ov.get("error")}
    if prof_stages:
        prof["stages"] = prof_stages
    if prof:
        doc["detail"]["profile"] = prof
    # per-query stats noop/overhead contract rides the trajectory like
    # the profiler's (byte_identical + within_2pct are the acceptance)
    qso = results.get("query_stats_overhead")
    if isinstance(qso, dict):
        doc["detail"]["query_stats"] = (
            qso if not _failed(qso) else {"error": qso.get("error")})
    # dogfood self-trace gate: noop byte-identity + <2% request
    # overhead, tracked like the profiler/query-stats contracts
    sto = results.get("selftrace_overhead")
    if isinstance(sto, dict):
        doc["detail"]["selftrace"] = (
            sto if not _failed(sto) else {"error": sto.get("error")})
    # search-freshness SLO: push->searchable p50/p99 under soak write
    # load + the write-path telemetry contracts (gauge-vs-canary
    # agreement, noop byte-identity, <2% ack overhead) — ROADMAP item
    # 4's acceptance instrumentation, tracked round over round
    fr = results.get("freshness")
    if isinstance(fr, dict):
        doc["detail"]["freshness"] = (
            fr if not _failed(fr) else {"error": fr.get("error")})
    if not ok:
        err = (single or {}).get(
            "error", "headline phase 'single' did not run")
        if err.startswith("skipped: not selected"):
            # an explicit BENCH_PHASES subset without the headline is a
            # deliberate partial run, not a device failure
            doc["partial"] = err
        else:
            doc["error"] = err
    # robustness contract: the chaos phase's noop/fallback/recovery
    # asserts, tracked round over round like the other noop contracts
    ch = results.get("chaos")
    if isinstance(ch, dict):
        doc["detail"]["chaos"] = (
            ch if not _failed(ch) else {"error": ch.get("error")})
    # packed-residency contract: physical-bytes saved, byte-identity,
    # and the fixed-budget residency/hit-ratio split (ISSUE 13) —
    # tracked round over round like the other noop contracts
    pk = results.get("packing")
    if isinstance(pk, dict):
        doc["detail"]["packing"] = (
            pk if not _failed(pk) else {"error": pk.get("error")})
    if breaker_wedged:
        # breaker-sourced wedge signal: some phase ended with its
        # breaker open/half-open — a real mid-run device failure
        doc["device_wedged"] = True
        doc.setdefault(
            "wedge_reason",
            "circuit breaker open at phase exit: "
            + ", ".join(f"{k}={v['state']}"
                        for k, v in sorted(breaker_wedged.items())))
        doc["detail"]["breaker"] = breaker_wedged
    degraded = results.get("degraded")
    if degraded:
        doc["degraded"] = degraded
        if isinstance(degraded, str) and degraded.startswith("cpu-fallback"):
            # the headline metric contract is TPU-vs-CPU; a CPU-only run
            # must read as an infra failure to consumers that only look at
            # value/vs_baseline — its numbers live in detail.configs only.
            # device_wedged + wedge_reason make the failure FIRST-CLASS in
            # the headline: r04/r05 recorded zeroed fallback numbers that
            # were indistinguishable from a real perf regression
            doc["value"] = 0
            doc["vs_baseline"] = 0
            doc["device_wedged"] = True
            doc["wedge_reason"] = degraded
            doc["error"] = ("TPU preflight failed; CPU-fallback numbers "
                            "recorded in detail.configs only")
    return doc


def orchestrate() -> int:
    # default budget covers a healthy full run (~12 min) plus ONE wedged
    # phase burning its largest deadline (1200 s); with several wedges the
    # remaining phases are skipped with explicit errors rather than lost
    budget = float(os.environ.get("BENCH_WATCHDOG_S", 3600))
    t_start = time.perf_counter()

    def time_left():
        if budget <= 0:
            return float("inf")
        return budget - (time.perf_counter() - t_start)

    ckpt_dir = os.environ.get(
        "BENCH_CKPT_DIR", os.path.join(_HERE, "benchmarks", ".bench_ckpt"))
    resume = os.environ.get("BENCH_RESUME", "0") not in ("0", "")
    os.makedirs(ckpt_dir, exist_ok=True)
    if not resume:
        for f in os.listdir(ckpt_dir):
            p = os.path.join(ckpt_dir, f)
            if os.path.isfile(p):
                os.unlink(p)

    results: dict = {}
    extra_env: dict = {}
    # one persistent XLA compile cache across every phase child (and
    # the scale phase's restart sub-child): later phases replay shared
    # kernel compiles from disk instead of re-paying them
    if not os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        extra_env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(
            ckpt_dir, "xla-cache")

    def emit_and_exit(rc: int) -> int:
        doc = _assemble(results)
        with open(os.path.join(ckpt_dir, "final.json"), "w") as f:
            json.dump(doc, f)
        print(json.dumps(doc), flush=True)
        return rc

    # a driver-side SIGTERM must still yield the completed phases' numbers
    # — and must not orphan the in-flight phase child on the device
    def on_term(signum, frame):
        if _current_child is not None:
            _kill_child(_current_child)
        results.setdefault("degraded", f"terminated by signal {signum}")
        doc = _assemble(results)
        try:
            with open(os.path.join(ckpt_dir, "final.json"), "w") as f:
                json.dump(doc, f)
        except OSError:
            pass
        print(json.dumps(doc), flush=True)
        os._exit(3)

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)  # Ctrl-C must not orphan a child

    # validate phase selection BEFORE spending minutes on preflight
    phase_order = [p for p in PHASES if p != "probe"]
    want = os.environ.get("BENCH_PHASES")
    if want:
        sel = [w.strip() for w in want.split(",") if w.strip()]
        unknown = sorted(set(sel) - set(PHASES))
        if unknown:  # fail fast — a typo must not silently drop a phase
            print(f"bench: unknown BENCH_PHASES {unknown}; "
                  f"valid: {sorted(PHASES)}", file=sys.stderr, flush=True)
            results["single"] = {"error":
                                 f"unknown BENCH_PHASES {unknown}"}
            return emit_and_exit(2)
        phase_order = [p for p in phase_order if p in sel]

    # --- preflight: short probe, then explicit CPU fallback ---
    # BENCH_PREFLIGHT_ATTEMPTS (default 1): r05 burned 3x60s on a wedged
    # device tunnel before falling back — one wedge is already a strong
    # signal, so fail over to CPU after the FIRST by default; operators
    # chasing a flaky (not dead) tunnel can raise it. The per-attempt
    # deadline is BENCH_TIMEOUT_PROBE (seconds).
    probe_deadline = float(os.environ.get(
        "BENCH_TIMEOUT_PROBE", PHASE_TIMEOUTS["probe"]))
    n_attempts = max(1, int(os.environ.get("BENCH_PREFLIGHT_ATTEMPTS", 1)))
    attempts = []
    for i in range(n_attempts):
        if time_left() < 10:
            break
        r = _run_child("probe", min(probe_deadline, time_left()),
                       ckpt_dir, extra_env)
        if not _failed(r):
            results["probe"] = r
            break
        attempts.append(r["error"])
        print(f"bench: preflight attempt {i + 1}/{n_attempts} failed: "
              f"{r['error']}", file=sys.stderr, flush=True)
    if "probe" not in results:
        if os.environ.get("BENCH_CPU_FALLBACK", "1") not in ("0", ""):
            extra_env["JAX_PLATFORMS"] = "cpu"
            r = _run_child("probe",
                           min(probe_deadline, max(time_left(), 10.0)),
                           ckpt_dir, extra_env)
            if not _failed(r):
                results["probe"] = r
                results["degraded"] = (
                    "cpu-fallback: device probe failed "
                    f"{len(attempts)}x ({attempts[-1] if attempts else 'budget'}); "
                    "numbers below are CPU, not TPU")
        if "probe" not in results:
            results["probe"] = {"error": "; ".join(attempts) or
                                "probe never ran (budget exhausted)"}
            results["single"] = {"error": "skipped: no healthy device "
                                          "(preflight probe failed)"}
            return emit_and_exit(3)

    # CPU fallback: the scale phases at full size stage multi-GB corpora
    # sized for a 16 GB-HBM chip — run them at REDUCED size instead of
    # skipping, so a degraded round still records a trajectory point for
    # every phase (r05 lost both scale series to one wedged tunnel).
    # BENCH_DEGRADED_SCALE=0 restores the old skip behavior.
    degraded_scale_env: dict = {}
    if results.get("degraded"):
        if os.environ.get("BENCH_DEGRADED_SCALE", "1") in ("0", ""):
            for p in ("scale_10k", "scale_large_blocks"):
                if p in phase_order:
                    phase_order.remove(p)
                    results[p] = {"error":
                                  "skipped: degraded cpu-fallback run"}
        else:
            degraded_scale_env = {
                "scale_10k": {
                    "BENCH_SCALE_BLOCKS": os.environ.get(
                        "BENCH_DEGRADED_SCALE_BLOCKS", "1000"),
                    "BENCH_SCALE_ENTRIES": "128",
                },
                "scale_large_blocks": {
                    "BENCH_LARGE_BLOCKS": os.environ.get(
                        "BENCH_DEGRADED_LARGE_BLOCKS", "24"),
                    "BENCH_LARGE_ENTRIES": "16384",
                    "BENCH_LARGE_BATCH_PAGES": "2048",
                },
            }

    for name in phase_order:
        ck = os.path.join(ckpt_dir, f"{name}.json")
        if resume and os.path.exists(ck):
            # only reuse a checkpoint whose platform + corpus knobs match
            # THIS run — a prior CPU-fallback or differently-sized run
            # must re-measure, not masquerade as current numbers
            fp_env = dict(os.environ)
            fp_env.update(extra_env)
            try:
                with open(ck) as f:
                    obj = json.load(f)
            except (OSError, json.JSONDecodeError):
                obj = None
            if (isinstance(obj, dict) and
                    obj.get("_fp") == _fingerprint(fp_env)):
                results[name] = obj["data"]
                continue
            print(f"bench: resume checkpoint for {name} is from a "
                  "different platform/config — re-running",
                  file=sys.stderr, flush=True)
        deadline = float(os.environ.get(
            f"BENCH_TIMEOUT_{name.upper()}", PHASE_TIMEOUTS[name]))
        remaining = time_left() - 20  # reserve for assembly/emission
        if remaining < 30:
            results[name] = {"error": "skipped: global bench budget "
                                      f"({budget:.0f}s) exhausted"}
            continue
        reason = ("global bench budget truncation — phase may be healthy"
                  if remaining < deadline
                  else "phase deadline — device tunnel likely wedged")
        phase_env = extra_env
        if name in degraded_scale_env:
            phase_env = dict(extra_env)
            phase_env.update(degraded_scale_env[name])
        t0 = time.perf_counter()
        results[name] = _run_child(name, min(deadline, remaining),
                                   ckpt_dir, phase_env,
                                   timeout_reason=reason)
        if name in degraded_scale_env and not _failed(results[name]) \
                and isinstance(results[name], dict):
            # mark the trajectory point: these numbers came from the
            # reduced degraded-mode corpus, not the full-size config
            results[name]["degraded_reduced_size"] = True
        status = "FAILED" if _failed(results[name]) else "ok"
        print(f"bench: phase {name} {status} "
              f"({time.perf_counter() - t0:.1f}s)",
              file=sys.stderr, flush=True)
        with open(os.path.join(ckpt_dir, "partial.json"), "w") as f:
            json.dump(_assemble(results), f)

    if "single" not in phase_order and "single" not in results:
        # deliberate partial selection: success = every SELECTED phase ok
        results["single"] = {"error": "skipped: not selected "
                                      "(BENCH_PHASES)"}
        sel_ok = all(not _failed(results.get(p, {"error": "missing"}))
                     for p in phase_order)
        if not sel_ok:
            return emit_and_exit(3)
        return emit_and_exit(4 if results.get("degraded") else 0)
    ok = not _failed(results.get("single", {"error": "missing"}))
    return emit_and_exit(0 if ok and not results.get("degraded")
                         else (4 if ok else 3))


def main() -> int:
    if len(sys.argv) >= 2 and sys.argv[1] == "--phase":
        if len(sys.argv) < 3 or sys.argv[2] not in PHASES:
            got = sys.argv[2] if len(sys.argv) >= 3 else "(missing)"
            print(json.dumps({"error": f"unknown phase {got!r}; "
                              f"valid: {sorted(PHASES)}"}), flush=True)
            return 2
        return _phase_main(sys.argv[2])
    return orchestrate()


if __name__ == "__main__":
    sys.exit(main())
