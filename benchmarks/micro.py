"""Micro-benchmarks — the reference's Go bench suite, re-hosted.

Mirrors (SURVEY.md §4 / §6):
  ingest push rate            modules/ingester/instance_test.go:632-656
  WAL append                  tempodb/wal/wal_test.go:473-490
  block write/read per codec  encoding/v2/streaming_block_test.go:298-331
  search under write load     modules/ingester/instance_search_test.go:401
  compaction throughput       tempodb/compactor_test.go:610

Each benchmark prints one JSON line:
  {"bench": "...", "value": N, "unit": "..."}
Run all: python -m benchmarks.micro [--quick]
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
import time

from tempo_tpu import tempopb
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.utils.test_data import make_trace

CODECS = ("none", "snappy", "lz4", "zstd", "gzip")


def _emit(bench: str, value: float, unit: str, **extra):
    print(json.dumps({"bench": bench, "value": round(value, 1),
                      "unit": unit, **extra}), flush=True)


def _objects(n, seed0=0, start=1_600_000_000):
    """[(trace_id, v2-object-bytes)], sorted by id."""
    from tempo_tpu.model import codec_for

    codec = codec_for("v2")
    out = []
    for i in range(n):
        tid = random_trace_id()
        tr = make_trace(tid, seed=seed0 + i, batches=1, spans_per_batch=4)
        out.append((tid, codec.marshal(tr, start + i % 600, start + i % 600 + 5)))
    return sorted(out)


def bench_ingest_push(n=2000):
    """Distributor→ingester push hot path in three shapes (VERDICT r4
    #4): one trace per push (worst case), 32 traces per push (realistic
    exporter batching), and with the metrics-generator forward disabled
    (the production distributor shape — the generator runs as its own
    target, so its consume cost is not on this process).

    Reference envelope: 15 MB/s/tenant ingestion-rate default
    (modules/overrides/limits.go:85-93). The remaining path to it from
    here is horizontal (distributor processes are independent; the ring
    replicates per trace) plus moving the generator's summary decode
    loop native like the regroup walk already is."""
    from tempo_tpu.modules import App, AppConfig

    def run(label, group, forward):
        tmp = tempfile.mkdtemp()
        app = App(AppConfig(wal_dir=os.path.join(tmp, "wal")))
        if not forward:
            app.distributor._forward_queue = None
        traces = [make_trace(random_trace_id(), seed=i) for i in range(n)]
        n_spans = sum(len(ss.spans) for t in traces for rs in t.batches
                      for ss in rs.scope_spans)
        mbytes = sum(t.ByteSize() for t in traces) / 1e6
        for tr in traces[:min(200, n)]:   # warm native path + caches
            app.push("bench", list(tr.batches))
        t0 = time.perf_counter()
        for i in range(0, len(traces), group):
            bb = [b for tr in traces[i:i + group] for b in tr.batches]
            app.push("bench", bb)
        dt = time.perf_counter() - t0
        app.shutdown()
        shutil.rmtree(tmp, ignore_errors=True)
        _emit(label, n_spans / dt, "spans/s", traces=n,
              traces_per_sec=round(n / dt), mb_per_sec=round(mbytes / dt, 2),
              native=app.distributor._use_native)

    run("ingest_push", 1, True)
    run("ingest_push_batched32", 32, True)
    run("ingest_push_no_generator", 1, False)


def bench_wal_append(n=500):
    """WAL append throughput (MiB/s of object bytes; the WAL is
    deliberately append-plain — page compression happens at block
    completion, so there is no per-codec axis here)."""
    from tempo_tpu.wal import WAL

    objs = _objects(n)
    total = sum(len(b) for _, b in objs)
    tmp = tempfile.mkdtemp()
    try:
        wal = WAL(tmp)
        blk = wal.new_block("bench")
        t0 = time.perf_counter()
        for tid, b in objs:
            blk.append(tid, b)
        dt = time.perf_counter() - t0
        _emit("wal_append", total / dt / (1 << 20), "MiB/s", objects=n)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_block_write_read(n=500):
    """Streaming-block write + full iterate read per codec (MiB/s)."""
    from tempo_tpu.backend import BlockMeta, open_backend
    from tempo_tpu.encoding.v2 import BackendBlock, StreamingBlock

    from tempo_tpu.encoding.v2.compression import encoding_usable

    objs = _objects(n)
    total = sum(len(b) for _, b in objs)
    for enc in CODECS:
        if not encoding_usable(enc):
            continue  # no native lib / wheel on this host
        backend = open_backend({"backend": "memory"})
        sb = StreamingBlock(BlockMeta(tenant_id="bench", encoding=enc))
        t0 = time.perf_counter()
        for i, (tid, b) in enumerate(objs):
            sb.add_object(tid, b, start=1000 + i, end=1100 + i)
        meta = sb.complete(backend)
        wdt = time.perf_counter() - t0
        blk = BackendBlock(backend, meta)
        t0 = time.perf_counter()
        m = sum(1 for _ in blk.iter_objects())
        rdt = time.perf_counter() - t0
        assert m == len(objs)
        _emit("block_write", total / wdt / (1 << 20), "MiB/s", codec=enc)
        _emit("block_read", total / rdt / (1 << 20), "MiB/s", codec=enc)


def bench_search_under_write_load(n_seed=1000, writers=2, duration_s=2.0):
    """Search QPS while concurrent pushes hammer the same instance."""
    from tempo_tpu.modules import App, AppConfig

    tmp = tempfile.mkdtemp()
    app = App(AppConfig(wal_dir=os.path.join(tmp, "wal")))
    for i in range(n_seed):
        app.push("bench", list(make_trace(random_trace_id(), seed=i).batches))
    stop = threading.Event()

    def writer(k):
        i = 0
        while not stop.is_set():
            tr = make_trace(random_trace_id(), seed=10_000 + k * 100_000 + i)
            app.push("bench", list(tr.batches))
            i += 1

    threads = [threading.Thread(target=writer, args=(k,), daemon=True)
               for k in range(writers)]
    for t in threads:
        t.start()
    req = tempopb.SearchRequest()
    req.limit = 20
    queries = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < duration_s:
        app.search("bench", req)
        queries += 1
    dt = time.perf_counter() - t0
    stop.set()
    for t in threads:
        t.join(timeout=2)
    app.shutdown()
    shutil.rmtree(tmp, ignore_errors=True)
    _emit("search_under_write_load", queries / dt, "queries/s",
          concurrent_writers=writers)


def bench_compaction(n=2000, n_blocks=4):
    """K-way merge compaction throughput (MiB/s of input bytes)."""
    from tempo_tpu.backend import open_backend
    from tempo_tpu.db import TempoDB, TempoDBConfig

    tmp = tempfile.mkdtemp()
    backend = open_backend({"backend": "memory"})
    db = TempoDB(backend, os.path.join(tmp, "wal"), TempoDBConfig())
    per = n // n_blocks
    now = int(time.time())
    for b in range(n_blocks):
        blk = db.wal.new_block("bench")
        for tid, obj in _objects(per, seed0=b * per, start=now - 300):
            blk.append(tid, obj)
        db.complete_block(blk)
    db.poll()
    metas = db.blocklist.metas("bench")
    total = sum(m.size for m in metas)
    t0 = time.perf_counter()
    out = db.compact_tenant_once("bench")
    dt = time.perf_counter() - t0
    assert out is not None, "selector found nothing to compact"
    _emit("compaction", total / dt / (1 << 20), "MiB/s",
          input_blocks=len(metas))
    shutil.rmtree(tmp, ignore_errors=True)


def main(quick: bool = False):
    scale = 0.1 if quick else 1.0
    bench_ingest_push(n=int(2000 * scale) or 50)
    bench_wal_append(n=int(500 * scale) or 20)
    bench_block_write_read(n=int(500 * scale) or 20)
    bench_search_under_write_load(
        n_seed=int(1000 * scale) or 30,
        duration_s=0.5 if quick else 2.0,
    )
    bench_compaction(n=int(2000 * scale) or 40)


if __name__ == "__main__":
    import sys

    main(quick="--quick" in sys.argv)
