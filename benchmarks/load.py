"""Staged-VU load harness — the k6 smoke/stress analog.

Mirrors the reference's integration/bench (smoke_test.js: concurrent
write + read + health scenarios with latency thresholds;
stress_test_write_path.js: staged VU ramp on the write path), driven
in-process against the real HTTP API by default or against a running
cluster with --url.

  python -m benchmarks.load smoke   [--vus 4]  [--duration 5]
  python -m benchmarks.load stress  [--stages 2:5,8:10,2:5] [--url http://...]

Each scenario prints one JSON line with req/s, p50/p99 latencies and
error rate, and exits non-zero when thresholds fail (k6 semantics):
smoke: error rate < 1%, write p99 < 500ms; stress: error rate < 5%.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.utils.test_data import make_trace


class Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.lat: list[float] = []
        self.errors = 0
        self.throttled = 0  # 429 backpressure: expected under overload

    def ok(self, dt: float):
        with self.lock:
            self.lat.append(dt)

    def err(self):
        with self.lock:
            self.errors += 1

    def throttle(self):
        with self.lock:
            self.throttled += 1

    def summary(self) -> dict:
        with self.lock:
            lat = sorted(self.lat)
            n = len(lat)
            total = n + self.errors + self.throttled
            pct = lambda p: lat[min(n - 1, int(p * n))] if n else None  # noqa: E731
            return {
                "requests": total,
                "errors": self.errors,
                "throttled": self.throttled,
                "error_rate": self.errors / total if total else 0.0,
                "p50_ms": round(pct(0.50) * 1000, 1) if n else None,
                "p99_ms": round(pct(0.99) * 1000, 1) if n else None,
            }


class Target:
    """HTTP target; spins an in-process single binary unless url given."""

    def __init__(self, url: str | None):
        self._own = None
        self._tmp = None
        if url:
            self.url = url.rstrip("/")
            return
        from tempo_tpu.api.http import HTTPApi, serve_http
        from tempo_tpu.modules import App, AppConfig

        self._tmp = tempfile.mkdtemp()
        self.app = App(AppConfig(wal_dir=os.path.join(self._tmp, "wal")))
        self.server = serve_http(HTTPApi(self.app), host="127.0.0.1", port=0)
        threading.Thread(target=self.server.serve_forever, daemon=True).start()
        self.url = f"http://127.0.0.1:{self.server.server_address[1]}"
        self._own = True

    def close(self):
        if self._own:
            self.server.shutdown()
            self.app.shutdown()
            shutil.rmtree(self._tmp, ignore_errors=True)


def _request(url: str, data: bytes | None = None, headers: dict | None = None,
             timeout: float = 10.0) -> bytes:
    req = urllib.request.Request(url, data=data, method="POST" if data else "GET")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.read()


def _vu_loop(target: Target, stats: dict, stop: threading.Event, vu_id: int,
             write_only: bool = False):
    """One virtual user: push a trace, read it back, hit /ready —
    the smoke_test.js scenario body."""
    tenant = "load"
    hdr = {"X-Scope-OrgID": tenant,
           "Content-Type": "application/x-protobuf"}
    rng = random.Random(vu_id)
    written: list[bytes] = []
    while not stop.is_set():
        tid = random_trace_id()
        body = make_trace(tid, seed=rng.randrange(1 << 30)).SerializeToString()
        t0 = time.perf_counter()
        try:
            _request(f"{target.url}/v1/traces", data=body, headers=hdr)
            stats["write"].ok(time.perf_counter() - t0)
            if not write_only:  # stress mode never reads these back
                written.append(tid)
        except urllib.error.HTTPError as e:
            # 429 is limit backpressure (live traces / ingest rate): the
            # CORRECT overload answer, tallied apart from failures —
            # the reference's k6 checks treat it the same way
            if e.code == 429:
                stats["write"].throttle()
            else:
                stats["write"].err()
        except (urllib.error.URLError, OSError):
            stats["write"].err()
        if write_only:
            continue
        if written and rng.random() < 0.5:
            rtid = rng.choice(written[-50:])
            t0 = time.perf_counter()
            try:
                _request(f"{target.url}/api/traces/{rtid.hex()}", headers=hdr)
                stats["read"].ok(time.perf_counter() - t0)
            except (urllib.error.URLError, OSError):
                stats["read"].err()
        t0 = time.perf_counter()
        try:
            _request(f"{target.url}/ready")
            stats["health"].ok(time.perf_counter() - t0)
        except (urllib.error.URLError, OSError):
            stats["health"].err()


def run_smoke(target: Target, vus: int, duration_s: float) -> int:
    stats = {k: Stats() for k in ("write", "read", "health")}
    stop = threading.Event()
    threads = [threading.Thread(target=_vu_loop, args=(target, stats, stop, i),
                                daemon=True) for i in range(vus)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    wall = time.perf_counter() - t0
    out = {"scenario": "smoke", "vus": vus, "duration_s": round(wall, 1)}
    for k, s in stats.items():
        out[k] = s.summary()
    total_reqs = sum(out[k]["requests"] for k in stats)
    out["rps"] = round(total_reqs / wall, 1)
    w = out["write"]
    # a broken read or health path must fail the smoke run too
    passed = (w["error_rate"] < 0.01
              and (w["p99_ms"] is not None and w["p99_ms"] < 500)
              and out["read"]["error_rate"] < 0.01
              and out["health"]["error_rate"] < 0.01)
    out["passed"] = passed
    print(json.dumps(out), flush=True)
    return 0 if passed else 1


def run_stress(target: Target, stages: list[tuple[int, float]]) -> int:
    """Staged write-path ramp: [(vus, seconds), ...]."""
    stats = {"write": Stats(), "read": Stats(), "health": Stats()}
    stop = threading.Event()
    threads: list[threading.Thread] = []
    t0 = time.perf_counter()
    for vus, secs in stages:
        while len(threads) < vus:
            t = threading.Thread(
                target=_vu_loop,
                args=(target, stats, stop, len(threads)),
                kwargs={"write_only": True}, daemon=True)
            t.start()
            threads.append(t)
        time.sleep(secs)  # VUs never scale down mid-run (k6 keeps them)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    wall = time.perf_counter() - t0
    w = stats["write"].summary()
    out = {"scenario": "stress_write_path",
           "peak_vus": max(v for v, _ in stages),
           "duration_s": round(wall, 1),
           "write": w,
           "rps": round(w["requests"] / wall, 1),
           "passed": w["error_rate"] < 0.05}
    print(json.dumps(out), flush=True)
    return 0 if out["passed"] else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser("tempo-tpu load harness")
    p.add_argument("scenario", choices=["smoke", "stress"])
    p.add_argument("--url", default=None,
                   help="target base URL (default: in-process single binary)")
    p.add_argument("--vus", type=int, default=4)
    p.add_argument("--duration", type=float, default=5.0)
    p.add_argument("--stages", default="2:3,6:5,2:3",
                   help="stress stages vus:seconds,...")
    args = p.parse_args(argv)
    target = Target(args.url)
    try:
        if args.scenario == "smoke":
            return run_smoke(target, args.vus, args.duration)
        stages = [(int(v), float(s)) for v, s in
                  (part.split(":") for part in args.stages.split(","))]
        return run_stress(target, stages)
    finally:
        target.close()


if __name__ == "__main__":
    sys.exit(main())
