"""Benchmark + load-test harnesses (reference SURVEY.md §4 parity).

  benchmarks/micro.py — the `make benchmark` analog: ingest push rate,
      WAL append per codec, block write/read per codec, search under
      concurrent write load, compaction throughput. Each prints a JSON
      line; `python -m benchmarks.micro` runs all.
  benchmarks/load.py — the k6 smoke/stress analog: staged virtual users
      driving the real HTTP API (in-process single binary by default, or
      --url for a running cluster), with latency thresholds.

The north-star TPU-vs-CPU scan benchmark stays at the repo root
(bench.py) — the driver runs that one on real hardware.
"""
