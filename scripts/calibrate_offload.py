#!/usr/bin/env python
"""Replay a dispatch-profiler dump through the offload planner offline.

Feed it a saved ``/debug/profile`` JSON (``curl :3200/debug/profile >
profile.json`` on a debug-enabled target) and it rebuilds the planner's
cost model from the recorded dispatches — device-probe rates from the
``dict_probe`` ring records, h2d/host-probe rates from the byte-carrying
aggregates — then prints the host/device decision table across a
cardinality sweep. Operators sanity-check a deployment's crossover
points (where would the planner flip?) without live traffic or a
restart; no process state is touched (a standalone planner instance, no
microbenchmark seed).

    python scripts/calibrate_offload.py profile.json
    python scripts/calibrate_offload.py profile.json \
        --terms 2 --shards 8 --avg-value-bytes 24 \
        --cardinalities 100000,1000000,10000000
"""

from __future__ import annotations

import argparse
import json
import sys


def _fmt_ms(seconds: float) -> str:
    return f"{seconds * 1e3:>12.1f}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="offline offload-planner calibration from a "
                    "/debug/profile dump")
    ap.add_argument("dump", help="path to a /debug/profile JSON dump")
    ap.add_argument("--terms", type=int, default=1,
                    help="tag terms per query (default 1)")
    ap.add_argument("--shards", type=int, default=1,
                    help="mesh shard count (adds the collective cost)")
    ap.add_argument("--avg-value-bytes", type=int, default=16,
                    help="mean dictionary value length (default 16)")
    ap.add_argument("--cardinalities", default=None,
                    help="comma-separated distinct-value counts "
                         "(default: 50k..10M sweep)")
    ap.add_argument("--recent", type=int, default=0,
                    help="show the N most recent replayed records")
    args = ap.parse_args(argv)

    from tempo_tpu.search.planner import OffloadPlanner

    with open(args.dump) as f:
        snap = json.load(f)

    # standalone instance: never mutates the process singleton, never
    # runs the microbenchmark seed — the dump IS the calibration
    p = OffloadPlanner(enabled=True, seed=False)
    n = p.ingest_profile_snapshot(snap)
    print(f"ingested {n} observations from {args.dump} "
          f"({snap.get('dispatches', 0)} recorded dispatches)")
    model = p.snapshot(recent=0)["cost_model"]
    print("\ncost model (seconds/byte; '-' = no observations, "
          "seed defaults apply):")
    for kind, r in model["rates"].items():
        v = r["seconds_per_byte"]
        print(f"  {kind:<14} {v if v is not None else '-'}"
              f"  ({r['observations']} obs)")
    for kind, fx in model["fixed"].items():
        v = fx["seconds"]
        print(f"  {kind:<14} {v if v is not None else '-'} s fixed"
              f"  ({fx['observations']} obs)")

    if args.cardinalities:
        cards = [int(c) for c in args.cardinalities.split(",") if c]
    else:
        cards = [50_000, 100_000, 316_000, 1_000_000, 3_160_000,
                 10_000_000]

    hdr = (f"{'distinct_vals':>13} {'dict_mb':>8} {'host_ms':>12} "
           f"{'device_cold_ms':>14} {'device_warm_ms':>14} "
           f"{'cold':>6} {'warm':>6}")
    print("\ndecision table "
          f"(terms={args.terms}, shards={args.shards}):")
    print(hdr)
    print("-" * len(hdr))
    prev_warm = None
    crossover = None
    for card in cards:
        nbytes = card * args.avg_value_bytes
        cold = p.decide_probe(n_vals=card, dict_bytes=nbytes,
                              n_terms=args.terms, resident=False,
                              n_shards=args.shards, site="offline")
        warm = p.decide_probe(n_vals=card, dict_bytes=nbytes,
                              n_terms=args.terms, resident=True,
                              staged_bytes=cold.inputs["staged_bytes"],
                              n_shards=args.shards, site="offline")
        print(f"{card:>13} {nbytes / (1 << 20):>8.1f}"
              f"{_fmt_ms(cold.predicted_host_s)}"
              f"{_fmt_ms(cold.predicted_device_s):>15}"
              f"{_fmt_ms(warm.predicted_device_s):>15}"
              f" {cold.target:>6} {warm.target:>6}")
        if prev_warm is not None and warm.target != prev_warm:
            crossover = card
        prev_warm = warm.target
    if crossover is not None:
        print(f"\nHBM-resident crossover between the sampled points "
              f"around {crossover} distinct values")
    else:
        print(f"\nno crossover in the sampled range: every resident "
              f"decision is '{prev_warm}'")

    if args.recent:
        print(f"\nlast {args.recent} replayed decisions:")
        for d in p.snapshot(recent=args.recent)["recent"]:
            print(f"  {json.dumps(d)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
