#!/usr/bin/env python
"""Run the tempo_tpu static-analysis suite (tempo_tpu/analysis/).

Usage:
    python scripts/check.py                      # whole package, human
    python scripts/check.py --json               # CI form
    python scripts/check.py --checker lock-order # one checker
    python scripts/check.py path/to/pkg          # another package root

Exit codes (CI contract):
    0   clean — no findings, no stale allowlist entries
    1   findings (or stale allowlist entries) — the rendered/JSON
        output lists each with path:line, checker id, fix hint and
        allowlist fingerprint
    2   usage or internal error (bad path, unknown checker, malformed
        allowlist)

The run is ONE in-process parse pass over the package (no subprocess
per file) — the same entry tier-1 uses via tests/test_static_analysis.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def main(argv=None) -> int:
    from tempo_tpu.analysis import (
        default_checkers,
        load_allowlist,
        run_suite,
    )
    from tempo_tpu.analysis.allowlist import AllowlistError, default_path
    from tempo_tpu.analysis.core import Package

    ap = argparse.ArgumentParser(
        prog="check.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("path", nargs="?",
                    default=os.path.join(_REPO, "tempo_tpu"),
                    help="package directory to analyze")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--checker", action="append", default=None,
                    help="run only this checker id (repeatable)")
    ap.add_argument("--allowlist", default=None,
                    help="allowlist file (default: "
                         "tempo_tpu/analysis/allowlist.toml when "
                         "analyzing the default package, none for an "
                         "alternate path; 'none' disables)")
    ap.add_argument("--list-checkers", action="store_true",
                    help="print checker ids and exit")
    args = ap.parse_args(argv)

    checkers = default_checkers()
    if args.list_checkers:
        for c in checkers:
            print(c.id)
        return 0
    if args.checker:
        ids = {c.id for c in checkers}
        unknown = [c for c in args.checker if c not in ids]
        if unknown:
            print(f"unknown checker(s): {unknown}; have {sorted(ids)}",
                  file=sys.stderr)
            return 2
        checkers = [c for c in checkers if c.id in args.checker]
    if not os.path.isdir(args.path):
        print(f"not a directory: {args.path}", file=sys.stderr)
        return 2
    try:
        pkg = Package.load(args.path)
        if args.allowlist == "none":
            allowlist = None
        elif args.allowlist is not None:
            allowlist = load_allowlist(args.allowlist)
        elif os.path.samefile(args.path,
                              os.path.join(_REPO, "tempo_tpu")):
            allowlist = load_allowlist(default_path())
        else:
            # an alternate package root: the repo allowlist's
            # fingerprints can't match anything there — applying it
            # would only manufacture spurious stale findings
            allowlist = None
        report = run_suite(pkg, checkers, allowlist)
    except AllowlistError as e:
        print(f"allowlist error: {e}", file=sys.stderr)
        return 2
    except SyntaxError as e:
        print(f"parse error: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(report.as_dict(), indent=2, sort_keys=True))
    else:
        print(report.render())
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
